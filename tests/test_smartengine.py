"""SmartEngine chain tests (python backend — the semantics reference).

Mirrors fluvio-smartengine's engine tests (engine/wasmtime/engine.rs:237-627,
transforms/filter.rs, transforms/aggregate.rs): filter, filter+map chain,
aggregate with accumulator, error short-circuit with partial output,
lookback happy/error paths, memory-limit enforcement, plus our SDK/DSL
surfaces (source-artifact loading, hook-vs-DSL equivalence,
TransformationConfig YAML).
"""

import asyncio

import pytest

from fluvio_tpu.models import lookup
from fluvio_tpu.protocol.record import Record
from fluvio_tpu.smartengine import (
    Lookback,
    SmartEngine,
    SmartModuleChainMetrics,
    SmartModuleConfig,
    TransformationConfig,
)
from fluvio_tpu.smartengine.engine import (
    EngineError,
    SmartModuleChainInitError,
    StoreMemoryExceeded,
)
from fluvio_tpu.smartmodule import SmartModuleInput, SmartModuleKind, load_source
from fluvio_tpu.smartmodule.types import SmartModuleLookbackError


def recs(*values: bytes):
    records = [Record(value=v) for v in values]
    for i, r in enumerate(records):
        r.offset_delta = i
    return records


def make_input(*values: bytes, base_offset=0, base_timestamp=-1):
    return SmartModuleInput.from_records(
        recs(*values), base_offset=base_offset, base_timestamp=base_timestamp
    )


def build_chain(*mods, engine=None):
    engine = engine or SmartEngine(backend="python")
    b = engine.builder()
    for module, config in mods:
        b.add_smart_module(config, module)
    return b.initialize()


class TestFilter:
    def test_regex_filter(self):
        chain = build_chain(
            (lookup("regex-filter"), SmartModuleConfig(params={"regex": "^a"}))
        )
        out = chain.process(make_input(b"apple", b"banana", b"avocado"))
        assert out.error is None
        assert [r.value for r in out.successes] == [b"apple", b"avocado"]

    def test_empty_chain_passthrough(self):
        chain = build_chain()
        out = chain.process(make_input(b"x", b"y"))
        assert [r.value for r in out.successes] == [b"x", b"y"]

    def test_filter_preserves_offsets(self):
        chain = build_chain(
            (lookup("regex-filter"), SmartModuleConfig(params={"regex": "keep"}))
        )
        out = chain.process(make_input(b"keep-0", b"drop", b"keep-2", base_offset=50))
        assert [r.offset_delta for r in out.successes] == [0, 2]


class TestChain:
    def test_filter_then_map(self):
        chain = build_chain(
            (lookup("regex-filter"), SmartModuleConfig(params={"regex": "fluvio"})),
            (lookup("json-map"), SmartModuleConfig(params={"field": "name"})),
        )
        out = chain.process(
            make_input(
                b'{"name":"fluvio","v":1}',
                b'{"name":"kafka","v":2}',
                b'{"name":"fluvio-tpu","v":3}',
            )
        )
        assert out.error is None
        assert [r.value for r in out.successes] == [b"FLUVIO", b"FLUVIO-TPU"]

    def test_metrics(self):
        chain = build_chain(
            (lookup("regex-filter"), SmartModuleConfig(params={"regex": "a"}))
        )
        metrics = SmartModuleChainMetrics()
        chain.process(make_input(b"abc", b"xyz"), metrics)
        assert metrics.records_out == 1
        assert metrics.fuel_used == 2  # two records through one instance
        assert metrics.bytes_in > 0


class TestAggregate:
    def test_running_sum_emitted_per_record(self):
        chain = build_chain((lookup("aggregate-sum"), SmartModuleConfig()))
        out = chain.process(make_input(b"1", b"2", b"3"))
        # reference semantics: running accumulator is each output's value
        assert [r.value for r in out.successes] == [b"1", b"3", b"6"]

    def test_accumulator_persists_across_process_calls(self):
        chain = build_chain((lookup("aggregate-sum"), SmartModuleConfig()))
        chain.process(make_input(b"10"))
        out = chain.process(make_input(b"5"))
        assert out.successes[0].value == b"15"

    def test_initial_accumulator_seed(self):
        chain = build_chain(
            (lookup("aggregate-sum"), SmartModuleConfig(initial_data=b"100"))
        )
        out = chain.process(make_input(b"1"))
        assert out.successes[0].value == b"101"

    def test_word_count(self):
        chain = build_chain((lookup("word-count"), SmartModuleConfig()))
        out = chain.process(make_input(b"hello world", b"one two  three"))
        assert [r.value for r in out.successes] == [b"2", b"5"]

    def test_windowed_sum(self):
        chain = build_chain(
            (lookup("windowed-sum"), SmartModuleConfig(params={"window_ms": "1000"}))
        )
        records = recs(b"1", b"2", b"3", b"4")
        # timestamps: two in window 0, two in window 1000
        records[0].timestamp_delta = 0
        records[1].timestamp_delta = 500
        records[2].timestamp_delta = 1000
        records[3].timestamp_delta = 1500
        inp = SmartModuleInput.from_records(records, base_offset=0, base_timestamp=0)
        out = chain.process(inp)
        assert [(r.key, r.value) for r in out.successes] == [
            (b"0", b"1"),
            (b"0", b"3"),
            (b"1000", b"3"),
            (b"1000", b"7"),
        ]


class TestArrayMap:
    def test_json_array_explode(self):
        chain = build_chain((lookup("array-map-json"), SmartModuleConfig()))
        out = chain.process(make_input(b'["a","b","c"]', b"[1,2]"))
        assert out.error is None
        assert [r.value for r in out.successes] == [b"a", b"b", b"c", b"1", b"2"]

    def test_non_array_is_error_with_partial_output(self):
        chain = build_chain((lookup("array-map-json"), SmartModuleConfig()))
        out = chain.process(make_input(b"[1]", b"not-an-array", b"[2]"))
        assert out.error is not None
        assert out.error.kind == SmartModuleKind.ARRAY_MAP
        assert out.error.offset == 1
        assert [r.value for r in out.successes] == [b"1"]  # partial output kept


class TestErrorSemantics:
    FAILING_FILTER = """
@smartmodule.filter
def fil(record):
    if record.value == b"boom":
        raise ValueError("exploded")
    return True
"""

    def test_error_short_circuits_with_partial_output(self):
        chain = build_chain(
            (self.FAILING_FILTER, SmartModuleConfig()),
        )
        out = chain.process(make_input(b"ok-1", b"boom", b"ok-2", base_offset=10))
        assert [r.value for r in out.successes] == [b"ok-1"]
        assert out.error is not None
        assert out.error.offset == 11  # absolute offset of the failing record
        assert out.error.record_value == b"boom"
        assert "exploded" in out.error.hint

    def test_error_stops_chain(self):
        chain = build_chain(
            (self.FAILING_FILTER, SmartModuleConfig()),
            (lookup("json-map"), SmartModuleConfig()),
        )
        out = chain.process(make_input(b"boom"))
        assert out.error is not None
        assert out.error.kind == SmartModuleKind.FILTER  # map never ran

    def test_init_failure_raises_chain_init_error(self):
        src = """
@smartmodule.init
def init(params):
    raise RuntimeError("bad init")

@smartmodule.filter
def fil(record):
    return True
"""
        with pytest.raises(SmartModuleChainInitError):
            build_chain((src, SmartModuleConfig()))

    def test_memory_limit(self):
        engine = SmartEngine(backend="python", store_max_memory=10)
        chain = build_chain(
            (lookup("regex-filter"), SmartModuleConfig(params={"regex": "x"})),
            engine=engine,
        )
        with pytest.raises(StoreMemoryExceeded):
            chain.process(make_input(b"x" * 100))


class TestLookback:
    COUNTER_SRC = """
state = {"seen": 0}

@smartmodule.look_back
def lb(record):
    if record.value == b"bad":
        raise ValueError("lookback hates this record")
    state["seen"] += 1

@smartmodule.filter
def fil(record):
    return state["seen"] > 0
"""

    def run(self, coro):
        return asyncio.get_event_loop_policy().new_event_loop().run_until_complete(coro)

    def test_lookback_happy_path(self):
        chain = build_chain(
            (self.COUNTER_SRC, SmartModuleConfig(lookback=Lookback.last_n(2))),
        )
        seen_configs = []

        async def read_fn(lookback):
            seen_configs.append(lookback)
            from fluvio_tpu.smartmodule.types import SmartModuleRecord

            return [SmartModuleRecord(Record(value=b"old"), 0, -1)]

        self.run(chain.look_back(read_fn))
        assert seen_configs[0].last == 2
        out = chain.process(make_input(b"now"))
        assert len(out.successes) == 1  # state hydrated from lookback

    def test_lookback_error(self):
        chain = build_chain(
            (self.COUNTER_SRC, SmartModuleConfig(lookback=Lookback.last_n(1))),
        )

        async def read_fn(lookback):
            from fluvio_tpu.smartmodule.types import SmartModuleRecord

            return [SmartModuleRecord(Record(value=b"bad"), 7, -1)]

        with pytest.raises(SmartModuleLookbackError) as ei:
            self.run(chain.look_back(read_fn))
        assert ei.value.offset == 7


class TestSdkSurface:
    def test_load_source_map_with_key(self):
        src = """
@smartmodule.map
def m(record):
    return (b"k", record.value.upper())
"""
        chain = build_chain((src, SmartModuleConfig()))
        out = chain.process(make_input(b"abc"))
        assert out.successes[0].key == b"k"
        assert out.successes[0].value == b"ABC"

    def test_load_source_requires_transform(self):
        with pytest.raises(ValueError):
            load_source("x = 1")

    def test_filter_map(self):
        src = """
@smartmodule.filter_map
def fm(record):
    n = int(record.value)
    if n % 2 == 0:
        return str(n // 2).encode()
    return None
"""
        chain = build_chain((src, SmartModuleConfig()))
        out = chain.process(make_input(b"2", b"3", b"8"))
        assert [r.value for r in out.successes] == [b"1", b"4"]

    def test_hook_vs_dsl_equivalence(self):
        """The Python-hook and DSL forms of built-ins must agree."""
        values = [
            b'{"name":"alpha","n":1}',
            b'{"n":2}',
            b'{"name":"Beta-2"}',
            b"not json",
        ]
        for name, params in [
            ("regex-filter", {"regex": "a"}),
            ("json-map", {"field": "name"}),
        ]:
            hook_mod = lookup(name)
            import fluvio_tpu.models.regex_filter as rf
            import fluvio_tpu.models.json_map as jm

            dsl_mod = (rf if name == "regex-filter" else jm).module(with_hooks=False)
            out_hook = build_chain((hook_mod, SmartModuleConfig(params=params))).process(
                make_input(*values)
            )
            out_dsl = build_chain((dsl_mod, SmartModuleConfig(params=params))).process(
                make_input(*values)
            )
            assert [(r.key, r.value) for r in out_hook.successes] == [
                (r.key, r.value) for r in out_dsl.successes
            ], name


class TestTransformationConfig:
    def test_yaml_parse(self):
        cfg = TransformationConfig.from_yaml(
            """
transforms:
  - uses: regex-filter
    with:
      regex: "^a"
  - uses: json-map
    lookback:
      last: 10
      age: 60000
"""
        )
        assert len(cfg.transforms) == 2
        assert cfg.transforms[0].uses == "regex-filter"
        assert cfg.transforms[0].with_params == {"regex": "^a"}
        assert cfg.transforms[1].lookback.last == 10
        assert cfg.transforms[1].lookback.age_ms == 60000

    def test_yaml_to_chain(self):
        cfg = TransformationConfig.from_yaml(
            "transforms:\n  - uses: regex-filter\n    with: {regex: b}\n"
        )
        step = cfg.transforms[0]
        chain = build_chain((lookup(step.uses), step.to_config()))
        out = chain.process(make_input(b"abc", b"xyz"))
        assert [r.value for r in out.successes] == [b"abc"]


LOOPING_FILTER = b"""
@smartmodule.filter
def spin(record):
    while True:
        pass
"""

LOOPING_INIT = b"""
@smartmodule.init
def init(params):
    while True:
        pass

@smartmodule.filter
def ok(record):
    return True
"""

LOOPING_LOOKBACK = b"""
@smartmodule.look_back
def lb(record):
    while True:
        pass

@smartmodule.filter
def ok(record):
    return True
"""


class TestHookMetering:
    """Fuel analog for arbitrary Python hooks (reference: wasmtime fuel,
    engine/wasmtime/state.rs:14,40-55): a looping module must produce a
    typed error in bounded time, never a wedged engine."""

    def test_looping_hook_becomes_transform_error(self):
        engine = SmartEngine(backend="python", hook_budget_ms=200)
        chain = build_chain(
            (LOOPING_FILTER, SmartModuleConfig()), engine=engine
        )
        out = chain.process(make_input(b"a", b"b"))
        assert out.error is not None
        assert "exceeded its execution budget" in str(out.error)
        assert out.successes == []

    def test_fuel_error_in_later_stage_reports_empty_output(self):
        """The looping stage produced nothing before the trap, so the
        chain reports the typed error with no successes (the failing
        stage's partial output — engine.rs:159-161 — is empty here)."""
        engine = SmartEngine(backend="python", hook_budget_ms=200)
        chain = build_chain(
            (lookup("regex-filter"), SmartModuleConfig(params={"regex": "keep"})),
            (LOOPING_FILTER, SmartModuleConfig()),
            engine=engine,
        )
        out = chain.process(make_input(b"keep-1", b"drop"))
        assert out.error is not None  # second stage exhausted its budget
        assert "execution budget" in str(out.error)
        assert out.successes == []

    def test_abandoned_hook_poisons_chain(self):
        """A hook that swallows injection leaves a live thread; the
        chain must fail fast on later calls instead of re-entering it."""
        src = b"""
@smartmodule.filter
def stubborn(record):
    import os
    while os.environ.get("FLUVIO_TEST_SPIN_B") != "stop":
        try:
            while os.environ.get("FLUVIO_TEST_SPIN_B") != "stop":
                pass
        except BaseException:
            pass
    return False
"""
        engine = SmartEngine(backend="python", hook_budget_ms=100)
        chain = build_chain((src, SmartModuleConfig()), engine=engine)
        try:
            out = chain.process(make_input(b"a"))
            assert out.error is not None
            import time
            t0 = time.time()
            out2 = chain.process(make_input(b"b"))
            assert out2.error is not None
            assert time.time() - t0 < 1.0  # fail-fast: hook never re-entered
        finally:
            import os as _os
            _os.environ["FLUVIO_TEST_SPIN_B"] = "stop"
            import time as _t
            _t.sleep(0.1)
            _os.environ.pop("FLUVIO_TEST_SPIN_B", None)

    def test_unmetered_by_default_in_library(self):
        assert SmartEngine().hook_budget_ms == 0

    @pytest.fixture
    def hung_hook(self, monkeypatch):
        """(hang, releases, metering) for abandonment tests: `hang`
        blocks inside Event.wait (C code — async-exc injection cannot
        land, so the watchdog must abandon the thread), the grace window
        is shrunk, and teardown releases every hung thread even when the
        test body fails: leaked spinners would otherwise count toward
        the process-wide limit for the rest of the session."""
        import threading

        from fluvio_tpu.smartengine import metering as m

        monkeypatch.setattr(m, "_KILL_GRACE_SECONDS", 0.2)
        releases = []

        def hang():
            ev = threading.Event()
            releases.append(ev)
            ev.wait()

        yield hang, releases, m
        for ev in releases:
            ev.set()

    def test_quarantine_is_per_module(self, hung_hook):
        """Module A abandoning its hook-thread limit quarantines ONLY A;
        module B still executes metered (reference parity: per-instance
        trap isolation, wasmtime/state.rs:40-55)."""
        hang, _, m = hung_hook
        for _ in range(m._MODULE_ABANDONED_LIMIT):
            with pytest.raises(m.SmartModuleFuelError) as ei:
                m.run_metered(hang, 50, "mod-a", key="key-a")
            assert ei.value.abandoned
        # module A is now refused without entering user code
        with pytest.raises(m.SmartModuleFuelError) as ei:
            m.run_metered(hang, 50, "mod-a", key="key-a")
        assert ei.value.quarantined == "module"
        # module B is untouched
        assert m.run_metered(lambda: 42, 500, "mod-b", key="key-b") == 42
        state = m.quarantine_state()
        assert "key-a" in state["quarantined_modules"]
        assert state["process_circuit_broken"] is False
        assert state["by_module"]["key-a"] == m._MODULE_ABANDONED_LIMIT

    def test_process_circuit_breaker_last_resort(self, hung_hook, monkeypatch):
        """Many DISTINCT modules abandoning threads trip the process-wide
        breaker: all metered execution is refused with a typed error
        naming the breaker (operator-visible via quarantine_state)."""
        hang, _, m = hung_hook
        monkeypatch.setattr(m, "_ABANDONED_LIMIT", 2)
        for key in ("cb-1", "cb-2"):
            with pytest.raises(m.SmartModuleFuelError):
                m.run_metered(hang, 50, key, key=key)
        with pytest.raises(m.SmartModuleFuelError) as ei:
            m.run_metered(lambda: 1, 500, "cb-innocent", key="cb-innocent")
        assert ei.value.quarantined == "process"
        assert m.quarantine_state()["process_circuit_broken"] is True

    def test_quarantine_lifts_when_abandoned_threads_die(self, hung_hook):
        """Quarantine is resource-scoped by design: it guards against
        live spinner threads, so when a module's abandoned hooks finally
        exit, the module may execute metered again (the error message
        promises exactly 'while they stay alive')."""
        import time

        hang, releases, m = hung_hook
        for _ in range(m._MODULE_ABANDONED_LIMIT):
            with pytest.raises(m.SmartModuleFuelError):
                m.run_metered(hang, 50, "mod-l", key="key-lift")
        with pytest.raises(m.SmartModuleFuelError) as ei:
            m.run_metered(lambda: 1, 500, "mod-l", key="key-lift")
        assert ei.value.quarantined == "module"
        for ev in releases:  # the spinners exit mid-test
            ev.set()
        for _ in range(100):  # wait for the released threads to die
            if m.quarantine_state()["by_module"].get("key-lift", 0) == 0:
                break
            time.sleep(0.05)
        assert m.run_metered(lambda: 7, 500, "mod-l", key="key-lift") == 7
        assert "key-lift" not in m.quarantine_state()["quarantined_modules"]

    def test_quarantine_visible_in_spu_metrics(self):
        from fluvio_tpu.spu.metrics import SpuMetrics

        d = SpuMetrics().to_dict()
        assert "hook_quarantine" in d
        assert set(d["hook_quarantine"]) == {
            "abandoned_hook_threads",
            "by_module",
            "quarantined_modules",
            "process_circuit_broken",
        }

    def test_module_identity_is_source_hash(self):
        """Adhoc modules all default to the same name; the meter key must
        come from the payload so quarantine cannot cross modules."""
        from fluvio_tpu.smartmodule.sdk import load_source

        a = load_source("@smartmodule.filter\ndef f(r):\n    return True\n")
        b = load_source("@smartmodule.filter\ndef f(r):\n    return False\n")
        assert a.meter_key and b.meter_key
        assert a.meter_key != b.meter_key
        # same source -> same key (quarantine survives chain rebuilds)
        a2 = load_source("@smartmodule.filter\ndef f(r):\n    return True\n")
        assert a2.meter_key == a.meter_key

    def test_aggregate_fuel_trap_poisons_chain(self):
        """An injected fuel error can land mid-accumulator-update: any
        trap on a stateful instance poisons the chain (ADVICE r4) so
        half-mutated state is never served."""
        src = b"""
@smartmodule.aggregate
def agg(acc, record):
    # pure-bytecode loop: async-exc injection lands and unwinds the
    # hook cleanly, so the trap is NOT abandoned (the previously
    # unpoisoned case)
    n = 0
    while True:
        n += 1
    return acc
"""
        engine = SmartEngine(backend="python", hook_budget_ms=100)
        chain = build_chain((src, SmartModuleConfig()), engine=engine)
        out = chain.process(make_input(b"1"))
        assert out.error is not None
        # the trap unwound cleanly (not abandoned) but the chain must
        # still fail fast: the accumulator may be inconsistent
        import time as _t

        t0 = _t.time()
        out2 = chain.process(make_input(b"2"))
        assert out2.error is not None
        assert _t.time() - t0 < 1.0

    def test_looping_init_is_chain_init_error(self):
        engine = SmartEngine(backend="python", hook_budget_ms=200)
        with pytest.raises(SmartModuleChainInitError) as ei:
            build_chain((LOOPING_INIT, SmartModuleConfig()), engine=engine)
        assert "execution budget" in str(ei.value)

    def test_looping_lookback_raises_fuel_error(self):
        from fluvio_tpu.smartengine.metering import SmartModuleFuelError

        engine = SmartEngine(backend="python", hook_budget_ms=200)
        chain = build_chain(
            (LOOPING_LOOKBACK, SmartModuleConfig(lookback=Lookback.last_n(1))),
            engine=engine,
        )

        async def read_fn(lookback):
            from fluvio_tpu.smartmodule.types import SmartModuleRecord

            return [SmartModuleRecord(Record(value=b"x"))]

        with pytest.raises(SmartModuleFuelError):
            asyncio.run(chain.look_back(read_fn))

    def test_hook_that_swallows_injection_still_errors(self, monkeypatch):
        """A bare except inside the hook cannot swallow the budget: the
        watchdog re-injects until the hook unwinds (or abandons it) and
        the caller gets the typed error either way. (The env kill-switch
        lets the abandoned thread exit AFTER the assertion so it does not
        burn the GIL for the rest of the test session.)"""
        import os as _os

        src = b"""
@smartmodule.filter
def stubborn(record):
    import os
    while os.environ.get("FLUVIO_TEST_SPIN_A") != "stop":
        try:
            while os.environ.get("FLUVIO_TEST_SPIN_A") != "stop":
                pass
        except Exception:
            pass
    return False
"""
        engine = SmartEngine(backend="python", hook_budget_ms=150)
        chain = build_chain((src, SmartModuleConfig()), engine=engine)
        try:
            out = chain.process(make_input(b"a"))
        finally:
            monkeypatch.setenv("FLUVIO_TEST_SPIN_A", "stop")
        assert out.error is not None
        assert "exceeded its execution budget" in str(out.error)

    def test_broker_stays_live_after_looping_module(self, tmp_path):
        """SPU serves a looping ad-hoc module: the stream gets an error
        response, and a healthy consume on the same broker still works."""
        import asyncio as aio

        from fluvio_tpu.client import ConsumerConfig, Fluvio, Offset
        from fluvio_tpu.schema.smartmodule import (
            SmartModuleInvocation,
            SmartModuleInvocationKind,
            SmartModuleInvocationWasm,
        )
        from fluvio_tpu.spu import SpuConfig, SpuServer
        from fluvio_tpu.storage.config import ReplicaConfig

        async def body():
            cfg = SpuConfig(
                id=7101,
                public_addr="127.0.0.1:0",
                log_base_dir=str(tmp_path),
                replication=ReplicaConfig(base_dir=str(tmp_path)),
            )
            cfg.smart_engine.hook_budget_ms = 300
            server = SpuServer(cfg)
            await server.start()
            server.ctx.create_replica("t", 0)
            client = await Fluvio.connect(server.public_addr)
            prod = await client.topic_producer("t", num_partitions=1)
            futs = [await prod.send(b"", f"v{i}".encode()) for i in range(3)]
            await prod.flush()
            for f in futs:
                await f.wait()

            consumer = await client.partition_consumer("t", 0)
            bad = ConsumerConfig(
                disable_continuous=True,
                smartmodules=[
                    SmartModuleInvocation(
                        wasm=SmartModuleInvocationWasm.adhoc(LOOPING_FILTER),
                        kind=SmartModuleInvocationKind.FILTER,
                    )
                ],
            )
            with pytest.raises(Exception) as ei:
                async for _ in consumer.stream(Offset.beginning(), bad):
                    pass
            assert "budget" in str(ei.value) or "SmartModule" in str(ei.value)

            # broker must still serve a healthy stream afterwards
            got = []
            consumer2 = await client.partition_consumer("t", 0)
            async for r in consumer2.stream(
                Offset.beginning(), ConsumerConfig(disable_continuous=True)
            ):
                got.append(r.value)
            assert got == [b"v0", b"v1", b"v2"]
            await client.close()
            await server.stop()

        asyncio.run(body())
