"""Multi-tenant soak harness tests (ISSUE-17).

The smoke members are the tier-1 acceptance set, deterministic on CPU:

- ``nominal`` quiesces and passes (rc 0) with the exactly-once ledger
  closed over the lag engine's offered/served/committed join;
- ``overload`` is detected as queueing collapse (rc 1), scored IN the
  shed-held state;
- ``fairness`` holds Jain >= 0.8 under a 4:1 Zipf skew with WRR floors
  armed, and the shed variant's queue-full drops land on the
  per-tenant accounting plane deterministically;
- the chaos leg drives shed + retry + churn + partition failover
  through the real broker path and the ledger still closes.

Full-size scenarios (``soak``, ``spike``) are ``slow``-marked: tier-1
runs with ``-m 'not slow'``.
"""

import dataclasses
import json

import pytest

from fluvio_tpu.soak import (
    SCENARIOS,
    Scenario,
    build_verdict,
    jain,
    parse_scenario,
    run_scenario,
    tenant_of_key,
    validate_verdict,
)
from fluvio_tpu.telemetry import TELEMETRY
from fluvio_tpu.telemetry import lag as lag_mod


@pytest.fixture(autouse=True)
def _clean_telemetry():
    TELEMETRY.reset()
    lag_mod.reset_engine()
    yield
    TELEMETRY.reset()
    lag_mod.reset_engine()


def _check(doc: dict, name: str) -> dict:
    return next(c for c in doc["checks"] if c["name"] == name)


# ---------------------------------------------------------------------------
# scenario grammar
# ---------------------------------------------------------------------------


class TestScenarioGrammar:
    def test_builtins_parse_as_themselves(self):
        for name, sc in SCENARIOS.items():
            assert parse_scenario(name) == sc

    def test_empty_spec_is_nominal(self):
        assert parse_scenario("") == SCENARIOS["nominal"]
        assert parse_scenario(None) == SCENARIOS["nominal"]

    def test_colon_overrides(self):
        sc = parse_scenario("overload:records=40,timeout_s=9.5")
        assert sc.name == "overload"
        assert sc.records == 40
        assert sc.timeout_s == 9.5
        assert sc.stop_on_hold is SCENARIOS["overload"].stop_on_hold

    def test_bare_overrides_overlay_nominal(self):
        sc = parse_scenario("tenants=8,skew=1.0,seed=3")
        assert (sc.tenants, sc.skew, sc.seed) == (8, 1.0, 3)
        assert sc.name == "nominal"

    def test_bool_coercion(self):
        assert parse_scenario("wrr=off").wrr is False
        assert parse_scenario("stop_on_hold=true").stop_on_hold is True
        with pytest.raises(ValueError):
            parse_scenario("wrr=maybe")

    def test_unknown_scenario_rejected(self):
        with pytest.raises(ValueError, match="unknown soak scenario"):
            parse_scenario("bogus")

    def test_unknown_key_rejected(self):
        with pytest.raises(ValueError, match="bad soak scenario field"):
            parse_scenario("nominal:warp=9")

    def test_name_not_overridable(self):
        with pytest.raises(ValueError):
            parse_scenario("nominal:name=other")

    def test_zipf_weights_skew(self):
        w = Scenario(tenants=4, skew=1.0).zipf_weights()
        assert w["t00"] / w["t03"] == pytest.approx(4.0)
        flat = Scenario(tenants=4, skew=0.0).zipf_weights()
        assert set(flat.values()) == {1.0}


# ---------------------------------------------------------------------------
# scorer primitives
# ---------------------------------------------------------------------------


class TestScorerPrimitives:
    def test_jain(self):
        assert jain([5, 5, 5, 5]) == pytest.approx(1.0)
        assert jain([1, 0, 0, 0]) == pytest.approx(0.25)
        assert jain([]) == 1.0
        assert jain([0, 0]) == 1.0
        # 4:1 skew over RAW throughput is unfair; over ratios it isn't
        assert jain([4, 1]) < 0.8 < jain([1.0, 1.0])

    def test_tenant_of_key(self):
        assert tenant_of_key("sig123@t03.s1/0") == "t03"
        assert tenant_of_key("stream@acme.events/2") == "acme"
        assert tenant_of_key("plain-topic/0") == "plain-topic"

    def test_verdict_schema_negatives(self):
        sc = parse_scenario("fairness")
        doc = build_verdict(sc, run_scenario(sc))
        assert validate_verdict(doc) == []
        missing = {k: v for k, v in doc.items() if k != "fairness"}
        assert any("fairness" in e for e in validate_verdict(missing))
        bad = dict(doc, verdict="maybe")
        assert any("vocabulary" in e for e in validate_verdict(bad))
        flipped = dict(doc, rc=1 - doc["rc"])
        assert any("rc must be 0 iff" in e for e in validate_verdict(flipped))


# ---------------------------------------------------------------------------
# tenant label cardinality (the bounded accounting plane)
# ---------------------------------------------------------------------------


class TestTenantCardinality:
    def test_overflow_fold_bounds_label_count(self, monkeypatch):
        monkeypatch.setattr(TELEMETRY, "tenant_cap", 2)
        for i in range(10):
            TELEMETRY.add_tenant_served(f"t{i:02d}", 1)
        served, _, _, _ = TELEMETRY.tenant_families()
        # two real labels + ONE overflow bucket; nothing dropped
        assert set(served) == {"t00", "t01", "_overflow"}
        assert sum(served.values()) == 10
        assert served["_overflow"] == 8

    def test_known_tenant_keeps_label_past_cap(self, monkeypatch):
        monkeypatch.setattr(TELEMETRY, "tenant_cap", 2)
        TELEMETRY.add_tenant_served("t00", 1)
        TELEMETRY.add_tenant_served("t01", 1)
        TELEMETRY.add_tenant_served("t99", 1)  # folds
        TELEMETRY.add_tenant_served("t00", 5)  # existing label sticks
        served, _, _, _ = TELEMETRY.tenant_families()
        assert served["t00"] == 6
        assert served["_overflow"] == 1

    def test_shed_and_age_families_fold_too(self, monkeypatch):
        monkeypatch.setattr(TELEMETRY, "tenant_cap", 1)
        for i in range(3):
            TELEMETRY.add_tenant_shed(f"t{i}")
            TELEMETRY.add_tenant_age(f"t{i}", 0.01)
        _, shed, _, ages = TELEMETRY.tenant_families()
        assert set(shed) == {"t0", "_overflow"}
        assert shed["_overflow"] == 2
        assert set(ages) == {"t0", "_overflow"}


# ---------------------------------------------------------------------------
# the tier-1 smoke scenarios (deterministic, CPU, fast)
# ---------------------------------------------------------------------------


class TestFairnessScenario:
    def test_wrr_holds_jain_under_zipf_skew(self):
        sc = parse_scenario("fairness")
        assert sc.skew == 1.0 and sc.tenants == 4  # 4:1 Zipf
        doc = build_verdict(sc, run_scenario(sc))
        assert doc["rc"] == 0 and doc["verdict"] == "pass"
        assert doc["fairness"] >= 0.8
        assert len(doc["tenants"]) == 4
        assert all(e["ratio"] <= 1.0 for e in doc["tenants"].values())
        assert _check(doc, "exactly_once_accounting")["ok"]

    def test_deterministic_queue_full_sheds_hit_tenant_plane(self):
        spec = "fairness:profile=spike,queue_depth=1,pump_per_tick=1"
        sc = parse_scenario(spec)
        runs = []
        for _ in range(2):
            TELEMETRY.reset()
            lag_mod.reset_engine()
            run = run_scenario(sc)
            runs.append(
                (
                    run["dropped"],
                    run["observed"]["admission"],
                    run["observed"]["tenants"]["shed"],
                )
            )
        # seeded schedule + synchronous pipeline = bit-identical runs
        assert runs[0] == runs[1]
        dropped, admission, shed_plane = runs[0]
        assert dropped > 0
        assert admission.get("queue-full", 0) > 0
        # every queue-full shed is tenant-attributed on the plane
        assert sum(shed_plane.values()) == admission["queue-full"]
        doc = build_verdict(sc, run_scenario(sc))
        # dropped records stay on the ledger as backlog: bounds mode
        assert doc["accounting"]["mode"] == "bounds"
        assert doc["accounting"]["ok"]
        assert doc["rc"] == 0  # shed but fair and far from collapse


class TestNominalBroker:
    def test_nominal_passes_exactly_once(self):
        sc = parse_scenario("nominal")
        run = run_scenario(sc)
        doc = build_verdict(sc, run)
        assert doc["rc"] == 0 and doc["verdict"] == "pass"
        assert run["quiesced"] is True
        assert run["churns"] == 1  # the churn leg really disconnected
        acct = doc["accounting"]
        assert acct["ok"] and acct["mode"] == "exact"
        assert acct["lag"] == 0
        # the client consumed every offered record exactly once, per
        # topic, across the disconnect/resume
        assert run["served_client"] == run["offered"]
        # the accounting plane agrees with the lag families
        assert acct["plane_served"] == acct["served"]

    def test_verdict_round_trips_through_json(self):
        sc = parse_scenario("nominal")
        doc = build_verdict(sc, run_scenario(sc))
        reloaded = json.loads(json.dumps(doc))
        assert validate_verdict(reloaded) == []
        assert reloaded == doc


class TestOverloadBroker:
    def test_overload_detected_as_queueing_collapse(self):
        sc = parse_scenario("overload")
        run = run_scenario(sc)
        doc = build_verdict(sc, run)
        assert doc["verdict"] == "collapse" and doc["rc"] == 1
        assert run["hold_seen"] is True
        collapse = doc["collapse"]
        assert collapse["detected"]
        assert collapse["held_now"] >= 1  # scored IN the held state
        assert collapse["served_ratio"] < sc.collapse_ratio
        # mid-collapse the ledger still closes as bounds: nothing lost
        acct = doc["accounting"]
        assert acct["ok"] and acct["mode"] == "bounds"
        assert acct["served"] + acct["lag"] >= acct["offered"]
        assert run["observed"]["admission"].get("breach-shed", 0) >= 1
        assert doc["shed_ratio"] > 0


class TestChaosBroker:
    def test_exactly_once_across_shed_retry_churn_failover(self):
        # warn-band lag target: sheds are probabilistic-with-retry, so
        # the stream recovers and drains (seed 3 is known to shed);
        # churn forces a real disconnect/resume and fail_group a
        # partition-placement failover mid-production
        sc = parse_scenario(
            "nominal:tenants=2,streams=1,records=16,lag_target=18,"
            "max_bytes=64,churn=1,partition_groups=2,fail_group=0,"
            "timeout_s=60,seed=3"
        )
        run = run_scenario(sc)
        doc = build_verdict(sc, run)
        assert doc["rc"] == 0 and doc["verdict"] == "pass"
        assert run["churns"] == 1
        assert run["failovers"] == 1
        assert run["quiesced"] is True
        acct = doc["accounting"]
        assert acct["ok"] and acct["mode"] == "exact"
        assert acct["lag"] == 0
        assert run["served_client"] == run["offered"]
        # any sheds that fired are tenant-attributed on the plane
        adm = run["observed"]["admission"]
        sheds = adm.get("warn-shed", 0) + adm.get("breach-shed", 0)
        shed_plane = run["observed"]["tenants"]["shed"]
        assert sum(shed_plane.values()) == sheds


# ---------------------------------------------------------------------------
# CLI surface
# ---------------------------------------------------------------------------


class TestSoakCli:
    def test_json_verdict_round_trips_schema(self, capsys):
        from fluvio_tpu.cli import main

        rc = main(["soak", "fairness", "--format", "json"])
        doc = json.loads(capsys.readouterr().out)
        assert validate_verdict(doc) == []
        assert rc == doc["rc"] == 0

    def test_overload_exits_nonzero(self, capsys):
        from fluvio_tpu.cli import main

        rc = main(["soak", "overload:timeout_s=30"])
        out = capsys.readouterr().out
        assert rc == 1
        assert "collapse" in out
        assert "no_queueing_collapse" in out and "FAIL" in out

    def test_bad_spec_is_usage_error(self, capsys):
        from fluvio_tpu.cli import main

        assert main(["soak", "not-a-scenario"]) == 1
        assert "unknown soak scenario" in capsys.readouterr().err

    def test_env_default_spec(self, capsys, monkeypatch):
        from fluvio_tpu.cli import main

        monkeypatch.setenv("FLUVIO_SOAK_SCENARIO", "fairness")
        rc = main(["soak", "--format", "json"])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert doc["scenario"] == "fairness"

    def test_list_names_builtins(self, capsys):
        from fluvio_tpu.cli import main

        assert main(["soak", "--list"]) == 0
        out = capsys.readouterr().out
        for name in SCENARIOS:
            assert name in out

    def test_table_renders_without_a_run(self):
        from fluvio_tpu.cli.soak import render_verdict_table

        sc = parse_scenario("fairness")
        doc = build_verdict(sc, run_scenario(sc))
        table = render_verdict_table(doc)
        assert "verdict pass" in table
        assert "t00" in table and "fairness" in table


# ---------------------------------------------------------------------------
# full scenarios (slow: excluded from tier-1)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestFullScenarios:
    def test_soak_full(self):
        sc = parse_scenario("soak")
        doc = build_verdict(sc, run_scenario(sc))
        assert validate_verdict(doc) == []
        assert doc["rc"] == 0

    def test_spike_full(self):
        sc = dataclasses.replace(parse_scenario("spike"), timeout_s=300.0)
        doc = build_verdict(sc, run_scenario(sc))
        assert validate_verdict(doc) == []
        assert doc["verdict"] in ("pass", "collapse", "fail")
