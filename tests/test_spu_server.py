"""Single-process integration tests: real server, real sockets, temp storage.

Mirrors the reference's SPU test pattern (fluvio-spu/src/services/public/
tests/{stream_fetch.rs,produce.rs}): boot the public server on a random
localhost port with a FileReplica in a temp dir, drive it with the real
client over real TCP, covering produce/fetch/stream-fetch, SmartModule
chains on both paths, isolation, acks, and error propagation.
"""

import asyncio

import pytest

from fluvio_tpu.client import (
    ConsumerConfig,
    Fluvio,
    Offset,
    ProducerConfig,
)
from fluvio_tpu.protocol.error import ErrorCode, FluvioError
from fluvio_tpu.schema.smartmodule import (
    SmartModuleInvocation,
    SmartModuleInvocationKind,
    SmartModuleInvocationWasm,
)
from fluvio_tpu.schema.spu import FetchRequest, Isolation
from fluvio_tpu.spu import SpuConfig, SpuServer
from fluvio_tpu.storage.config import ReplicaConfig

FILTER_SM = b"""
@smartmodule.filter(dsl=dsl.FilterProgram(
    predicate=dsl.Contains(arg=dsl.Value(), literal=b"keep")))
def fil(record):
    return b"keep" in record.value
"""

UPPER_MAP_SM = b"""
@smartmodule.map(dsl=dsl.MapProgram(value=dsl.Upper(arg=dsl.Value())))
def m(record):
    return record.value.upper()
"""

ERROR_SM = b"""
@smartmodule.map
def m(record):
    if record.value == b"boom":
        raise ValueError("exploded")
    return record.value
"""


def adhoc(payload: bytes, **kw) -> SmartModuleInvocation:
    return SmartModuleInvocation(
        wasm=SmartModuleInvocationWasm.adhoc(payload), **kw
    )


@pytest.fixture()
def spu(tmp_path):
    """A running SPU with one replica, plus a loop to drive the tests."""
    loop = asyncio.new_event_loop()
    config = SpuConfig(
        id=5001,
        public_addr="127.0.0.1:0",
        log_base_dir=str(tmp_path),
        replication=ReplicaConfig(base_dir=str(tmp_path)),
    )
    config.smart_engine.backend = "auto"
    server = SpuServer(config)

    async def boot():
        await server.start()
        server.ctx.create_replica("topic", 0)

    loop.run_until_complete(boot())
    try:
        yield server, loop
    finally:
        loop.run_until_complete(server.stop())
        loop.close()


async def produce_values(addr, values, topic="topic", config=None):
    client = await Fluvio.connect(addr)
    producer = await client.topic_producer(topic, config=config)
    futs = [await producer.send(None, v) for v in values]
    await producer.flush()
    metas = [await f.wait() for f in futs]
    await producer.close()
    await client.close()
    return metas


async def consume_values(addr, offset=None, topic="topic", config=None):
    client = await Fluvio.connect(addr)
    consumer = await client.partition_consumer(topic, 0)
    config = config or ConsumerConfig(disable_continuous=True)
    out = []
    async for record in consumer.stream(offset or Offset.beginning(), config):
        out.append(record)
    await client.close()
    return out


class TestProduceConsume:
    def test_roundtrip(self, spu):
        server, loop = spu
        values = [f"message-{i}".encode() for i in range(100)]

        async def run():
            metas = await produce_values(server.public_addr, values)
            assert [m.offset for m in metas] == list(range(100))
            records = await consume_values(server.public_addr)
            assert [r.value for r in records] == values
            assert [r.offset for r in records] == list(range(100))

        loop.run_until_complete(run())

    def test_produce_with_keys(self, spu):
        server, loop = spu

        async def run():
            client = await Fluvio.connect(server.public_addr)
            producer = await client.topic_producer("topic")
            fut = await producer.send(b"k1", b"v1")
            await producer.flush()
            meta = await fut.wait()
            assert meta.offset == 0
            records = await consume_values(server.public_addr)
            assert records[0].key == b"k1"
            assert records[0].value == b"v1"
            await producer.close()
            await client.close()

        loop.run_until_complete(run())

    def test_consume_from_absolute_offset(self, spu):
        server, loop = spu

        async def run():
            await produce_values(
                server.public_addr, [f"m{i}".encode() for i in range(10)]
            )
            records = await consume_values(
                server.public_addr, offset=Offset.absolute(7)
            )
            assert [r.value for r in records] == [b"m7", b"m8", b"m9"]

        loop.run_until_complete(run())

    def test_consume_from_end_sees_only_new(self, spu):
        server, loop = spu

        async def run():
            await produce_values(server.public_addr, [b"old-1", b"old-2"])

            client = await Fluvio.connect(server.public_addr)
            consumer = await client.partition_consumer("topic", 0)
            received = []

            async def consume_two():
                async for rec in consumer.stream(
                    Offset.end(), ConsumerConfig()
                ):
                    received.append(rec.value)
                    if len(received) == 2:
                        break

            task = asyncio.ensure_future(consume_two())
            await asyncio.sleep(0.1)
            await produce_values(server.public_addr, [b"new-1", b"new-2"])
            await asyncio.wait_for(task, timeout=5)
            assert received == [b"new-1", b"new-2"]
            await client.close()

        loop.run_until_complete(run())

    def test_multiple_produce_rounds_accumulate(self, spu):
        server, loop = spu

        async def run():
            await produce_values(server.public_addr, [b"a"])
            await produce_values(server.public_addr, [b"b", b"c"])
            records = await consume_values(server.public_addr)
            assert [r.value for r in records] == [b"a", b"b", b"c"]
            assert [r.offset for r in records] == [0, 1, 2]

        loop.run_until_complete(run())

    def test_fetch_offsets(self, spu):
        server, loop = spu

        async def run():
            await produce_values(server.public_addr, [b"x"] * 5)
            client = await Fluvio.connect(server.public_addr)
            consumer = await client.partition_consumer("topic", 0)
            offsets = await consumer.fetch_offsets()
            assert offsets.start_offset == 0
            assert offsets.leo == 5
            assert offsets.hw == 5  # rf=1: HW advances with LEO
            await client.close()

        loop.run_until_complete(run())

    def test_one_shot_fetch(self, spu):
        server, loop = spu

        async def run():
            await produce_values(server.public_addr, [b"f1", b"f2"])
            from fluvio_tpu.transport.versioned import VersionedSerialSocket

            sock = await VersionedSerialSocket.connect(server.public_addr)
            resp = await sock.send_receive(
                FetchRequest(topic="topic", partition=0, fetch_offset=0)
            )
            assert resp.partition.error_code == ErrorCode.NONE
            values = [
                r.value
                for b in resp.partition.records.batches
                for r in b.memory_records()
            ]
            assert values == [b"f1", b"f2"]
            await sock.close()

        loop.run_until_complete(run())

    def test_unknown_partition_errors(self, spu):
        server, loop = spu

        async def run():
            with pytest.raises(FluvioError) as e:
                await consume_values(server.public_addr, topic="nope")
            assert e.value.code == ErrorCode.NOT_LEADER_FOR_PARTITION

        loop.run_until_complete(run())


class TestSmartModuleStreams:
    def test_consume_with_filter(self, spu):
        server, loop = spu

        async def run():
            await produce_values(
                server.public_addr,
                [b"keep-1", b"drop-1", b"keep-2", b"drop-2", b"keep-3"],
            )
            config = ConsumerConfig(
                disable_continuous=True,
                smartmodules=[adhoc(FILTER_SM, kind=SmartModuleInvocationKind.FILTER)],
            )
            records = await consume_values(server.public_addr, config=config)
            assert [r.value for r in records] == [b"keep-1", b"keep-2", b"keep-3"]

        loop.run_until_complete(run())

    def test_admission_shed_holds_stream_without_loss(self, spu):
        """ISSUE-11 integration: with the admission gate armed and the
        chain's health BREACHING, the stream handler HOLDS slices (no
        error to the client, no records skipped) and delivers everything
        once the verdict recovers — the typed decline is backpressure,
        never an exception, and a shed slice moves no dispatch gauge."""
        from fluvio_tpu.admission import AdmissionController
        from fluvio_tpu.telemetry import TELEMETRY

        server, loop = spu

        class RecoveringSlo:
            """Breach for the first few evaluations, then healthy."""

            def __init__(self, breaches: int) -> None:
                self.left = breaches

            def evaluate(self, tick=True):
                if self.left > 0:
                    self.left -= 1
                    return {
                        "enabled": True,
                        "chains": {"_engine": {"verdict": "breach",
                                               "rules": {}}},
                    }
                return {"enabled": True, "chains": {}}

        ctl = AdmissionController(
            slo_engine=RecoveringSlo(3), refresh_s=0.0,
            tokens=1e9, refill=1e9,
        )
        from fluvio_tpu import admission as admission_pkg

        admission_pkg.set_gate(ctl)
        shed0 = dict(TELEMETRY.admission)
        g0 = TELEMETRY.gauge_value("inflight_queue_depth")

        async def run():
            await produce_values(
                server.public_addr,
                [b"keep-1", b"drop-1", b"keep-2", b"drop-2", b"keep-3"],
            )
            config = ConsumerConfig(
                disable_continuous=True,
                smartmodules=[adhoc(FILTER_SM, kind=SmartModuleInvocationKind.FILTER)],
            )
            records = await consume_values(server.public_addr, config=config)
            # every record delivered exactly once despite the sheds
            assert [r.value for r in records] == [
                b"keep-1", b"keep-2", b"keep-3",
            ]

        try:
            loop.run_until_complete(run())
        finally:
            admission_pkg.reset_gate()  # later tests run un-gated
        sheds = sum(
            v - shed0.get(k, 0)
            for k, v in TELEMETRY.admission.items()
            if k == "breach-shed"
        )
        assert sheds >= 1, TELEMETRY.admission
        # a shed slice never reached dispatch: the gauge is untouched
        # at quiesce (finished slices released theirs)
        assert TELEMETRY.gauge_value("inflight_queue_depth") == g0

    def test_consume_with_filter_map_chain(self, spu):
        server, loop = spu

        async def run():
            await produce_values(server.public_addr, [b"keep-a", b"drop", b"keep-b"])
            config = ConsumerConfig(
                disable_continuous=True,
                smartmodules=[
                    adhoc(FILTER_SM, kind=SmartModuleInvocationKind.FILTER),
                    adhoc(UPPER_MAP_SM, kind=SmartModuleInvocationKind.MAP),
                ],
            )
            records = await consume_values(server.public_addr, config=config)
            assert [r.value for r in records] == [b"KEEP-A", b"KEEP-B"]

        loop.run_until_complete(run())

    def test_predefined_smartmodule_resolution(self, spu):
        server, loop = spu
        server.ctx.smartmodules.insert("my-filter", FILTER_SM)

        async def run():
            await produce_values(server.public_addr, [b"keep", b"drop"])
            config = ConsumerConfig(
                disable_continuous=True,
                smartmodules=[
                    SmartModuleInvocation(
                        wasm=SmartModuleInvocationWasm.predefined("my-filter")
                    )
                ],
            )
            records = await consume_values(server.public_addr, config=config)
            assert [r.value for r in records] == [b"keep"]

        loop.run_until_complete(run())

    def test_missing_predefined_module_errors(self, spu):
        server, loop = spu

        async def run():
            await produce_values(server.public_addr, [b"x"])
            config = ConsumerConfig(
                disable_continuous=True,
                smartmodules=[
                    SmartModuleInvocation(
                        wasm=SmartModuleInvocationWasm.predefined("ghost")
                    )
                ],
            )
            with pytest.raises(FluvioError) as e:
                await consume_values(server.public_addr, config=config)
            assert e.value.code == ErrorCode.SMARTMODULE_NOT_FOUND

        loop.run_until_complete(run())

    def test_transform_error_propagates(self, spu):
        server, loop = spu

        async def run():
            await produce_values(server.public_addr, [b"fine", b"boom", b"after"])
            config = ConsumerConfig(
                disable_continuous=True,
                smartmodules=[adhoc(ERROR_SM, kind=SmartModuleInvocationKind.MAP)],
            )
            with pytest.raises(FluvioError) as e:
                await consume_values(server.public_addr, config=config)
            assert e.value.code == ErrorCode.SMARTMODULE_RUNTIME_ERROR
            assert "exploded" in e.value.message

        loop.run_until_complete(run())

    def test_producer_side_smartmodule(self, spu):
        server, loop = spu

        async def run():
            config = ProducerConfig(
                smartmodules=[adhoc(UPPER_MAP_SM, kind=SmartModuleInvocationKind.MAP)]
            )
            await produce_values(server.public_addr, [b"abc", b"def"], config=config)
            records = await consume_values(server.public_addr)
            assert [r.value for r in records] == [b"ABC", b"DEF"]

        loop.run_until_complete(run())


class TestIsolation:
    def test_read_committed_produce(self, spu):
        server, loop = spu

        async def run():
            config = ProducerConfig(isolation=Isolation.READ_COMMITTED)
            metas = await produce_values(server.public_addr, [b"c1"], config=config)
            assert metas[0].offset == 0
            records = await consume_values(
                server.public_addr,
                config=ConsumerConfig(
                    disable_continuous=True, isolation=Isolation.READ_COMMITTED
                ),
            )
            assert [r.value for r in records] == [b"c1"]

        loop.run_until_complete(run())


class TestMultiplexing:
    def test_concurrent_serial_requests(self, spu):
        server, loop = spu

        async def run():
            await produce_values(server.public_addr, [b"m"] * 3)
            from fluvio_tpu.schema.spu import FetchOffsetsRequest
            from fluvio_tpu.transport.versioned import VersionedSerialSocket

            sock = await VersionedSerialSocket.connect(server.public_addr)
            results = await asyncio.gather(
                *(
                    sock.send_receive(
                        FetchOffsetsRequest(topic="topic", partition=0)
                    )
                    for _ in range(20)
                )
            )
            assert all(r.leo == 3 for r in results)
            await sock.close()

        loop.run_until_complete(run())

    def test_stream_and_serial_share_connection(self, spu):
        server, loop = spu

        async def run():
            await produce_values(server.public_addr, [b"s1", b"s2"])
            client = await Fluvio.connect(server.public_addr)
            consumer = await client.partition_consumer("topic", 0)
            # stream + a serial offsets request on the same multiplexer
            records = []
            async for rec in consumer.stream(
                Offset.beginning(), ConsumerConfig(disable_continuous=True)
            ):
                records.append(rec)
                offsets = await consumer.fetch_offsets()
                assert offsets.leo == 2
            assert len(records) == 2
            await client.close()

        loop.run_until_complete(run())


DOUBLE_MAP_SM = b"""
@smartmodule.map(dsl=dsl.MapProgram(
    value=dsl.Concat(args=[dsl.Value(), dsl.Value()])))
def m(record):
    return record.value + record.value
"""


class TestPipelinedStream:
    """The dispatch-ahead stream loop (stateless TPU chains)."""

    def test_multi_slice_stream_through_chain(self, spu):
        server, loop = spu

        async def run():
            # several produce rounds -> several stored batches/slices
            for r in range(4):
                await produce_values(
                    server.public_addr,
                    [f"keep-{r}-{i}".encode() for i in range(20)]
                    + [f"drop-{r}-{i}".encode() for i in range(10)],
                )
            cfg = ConsumerConfig(
                disable_continuous=True,
                smartmodules=[
                    adhoc(FILTER_SM, kind=SmartModuleInvocationKind.FILTER)
                ],
            )
            records = await consume_values(server.public_addr, config=cfg)
            values = [r.value for r in records]
            assert len(values) == 80
            expect = [
                f"keep-{r}-{i}".encode() for r in range(4) for i in range(20)
            ]
            assert values == expect
            # survivors keep their stored offsets
            offsets = [r.offset for r in records]
            assert offsets == sorted(offsets)
            assert offsets[0] == 0 and offsets[-1] == 3 * 30 + 19
            m = server.ctx.metrics.smartmodule
            assert m.fastpath_slices > 0
            assert m.fallback_slices == 0
        loop.run_until_complete(run())

    def test_truncation_discards_speculative_slice(self, spu):
        """A byte-doubling map makes output > max_bytes, forcing the
        max_bytes cutoff mid-slice — the pipelined loop must discard its
        speculative dispatch and re-read from the true consume point."""
        server, loop = spu

        async def run():
            values = [b"x" * 100 for _ in range(50)]
            await produce_values(server.public_addr, values)
            cfg = ConsumerConfig(
                disable_continuous=True,
                max_bytes=600,  # output slices ~2x input: forces cutoffs
                smartmodules=[
                    adhoc(DOUBLE_MAP_SM, kind=SmartModuleInvocationKind.MAP)
                ],
            )
            records = await consume_values(server.public_addr, config=cfg)
            assert [r.value for r in records] == [b"x" * 200] * 50
            assert [r.offset for r in records] == list(range(50))
        loop.run_until_complete(run())


class TestRetentionCleaner:
    """The background retention sweep over led replicas (cleaner.rs:20,56)."""

    def test_oversize_replica_sheds_segments_in_running_spu(self, tmp_path):
        loop = asyncio.new_event_loop()
        config = SpuConfig(
            id=5002,
            public_addr="127.0.0.1:0",
            log_base_dir=str(tmp_path),
            replication=ReplicaConfig(
                base_dir=str(tmp_path),
                segment_max_bytes=2048,      # force frequent rolls
                max_partition_size=6144,     # keep ~3 segments
            ),
            cleaner_interval_seconds=0.05,
        )
        server = SpuServer(config)

        async def run():
            await server.start()
            server.ctx.create_replica("topic", 0)
            # write well past the partition budget, in rounds so the
            # active segment rolls many times
            values = [f"payload-{i:04d}-{'x' * 80}".encode() for i in range(300)]
            for lo in range(0, 300, 20):
                await produce_values(server.public_addr, values[lo : lo + 20])
            leader = server.ctx.leader_for("topic", 0)

            def total_size():
                return leader.storage.active_segment.size + sum(
                    s.size for s in leader.storage.prev_segments.values()
                )

            for _ in range(100):  # wait for the background sweep
                if total_size() <= 6144:
                    break
                await asyncio.sleep(0.05)
            assert total_size() <= 6144, "cleaner never brought size under budget"
            # the log start advanced past the shed segments but the tail
            # stays consumable through the normal path
            start = leader.storage.get_log_start_offset()
            assert start > 0
            records = await consume_values(server.public_addr)
            assert [r.offset for r in records] == list(range(start, 300))
            assert records[0].value == values[start]
            await server.stop()

        try:
            loop.run_until_complete(run())
        finally:
            loop.close()

    def test_age_based_shedding_sweep(self, tmp_path):
        loop = asyncio.new_event_loop()
        config = SpuConfig(
            id=5003,
            public_addr="127.0.0.1:0",
            log_base_dir=str(tmp_path),
            replication=ReplicaConfig(
                base_dir=str(tmp_path),
                segment_max_bytes=1024,
                retention_seconds=1,
            ),
            cleaner_interval_seconds=0,  # manual sweeps
        )
        server = SpuServer(config)

        async def run():
            await server.start()
            server.ctx.create_replica("topic", 0)
            for _ in range(8):
                await produce_values(
                    server.public_addr, [b"old-" + bytes(60) for _ in range(5)]
                )
            leader = server.ctx.leader_for("topic", 0)
            assert leader.storage.prev_segments
            # nothing is old enough yet
            assert server.cleaner.sweep() == 0
            await asyncio.sleep(1.2)
            shed = server.cleaner.sweep()
            assert shed > 0
            assert not leader.storage.prev_segments
            await server.stop()

        try:
            loop.run_until_complete(run())
        finally:
            loop.close()


class TestVersionNegotiation:
    """Per-API range negotiation (versioned.rs:218): the pinned version is
    the highest in the intersection of client and server ranges."""

    def test_lookup_picks_intersection_max(self):
        from fluvio_tpu.protocol.api import ApiVersionKey, ApiVersionsResponse
        from fluvio_tpu.transport.versioned import (
            VersionedSerialSocket,
            VersionMismatch,
        )

        versions = ApiVersionsResponse(
            api_keys=[ApiVersionKey(FetchRequest.API_KEY, 0, 1)]
        )
        sock = VersionedSerialSocket(multiplexer=None, versions=versions)
        # client max above server max -> talk down to the server's max
        assert FetchRequest.MAX_API_VERSION >= 1
        assert sock.lookup_version(FetchRequest()) == 1

    def test_disjoint_ranges_raise_typed_error(self):
        from fluvio_tpu.protocol.api import ApiVersionKey, ApiVersionsResponse
        from fluvio_tpu.transport.versioned import (
            VersionedSerialSocket,
            VersionMismatch,
        )

        # server only speaks versions newer than the client can encode
        future = FetchRequest.MAX_API_VERSION + 5
        versions = ApiVersionsResponse(
            api_keys=[ApiVersionKey(FetchRequest.API_KEY, future, future + 1)]
        )
        sock = VersionedSerialSocket(multiplexer=None, versions=versions)
        import pytest as _pytest

        with _pytest.raises(VersionMismatch) as e:
            sock.lookup_version(FetchRequest())
        assert "server supports" in str(e.value)

    def test_unknown_api_key_raises(self):
        from fluvio_tpu.protocol.api import ApiVersionsResponse
        from fluvio_tpu.transport.versioned import (
            VersionedSerialSocket,
            VersionMismatch,
        )

        sock = VersionedSerialSocket(
            multiplexer=None, versions=ApiVersionsResponse(api_keys=[])
        )
        import pytest as _pytest

        with _pytest.raises(VersionMismatch):
            sock.lookup_version(FetchRequest())

    def test_old_version_client_against_live_server(self, spu):
        """A 'downgraded' client (server table doctored to max=0) still
        produces and consumes — the wire stays compatible at v0."""
        server, loop = spu

        async def run():
            from fluvio_tpu.protocol.api import ApiVersionKey
            from fluvio_tpu.transport.versioned import VersionedSerialSocket

            sock = await VersionedSerialSocket.connect(server.public_addr)
            # doctor the negotiated table: pretend the server is old
            for k in sock.versions.api_keys:
                k.max_version = 0
            resp = await sock.send_receive(
                FetchRequest(topic="topic", partition=0, fetch_offset=0)
            )
            assert resp.partition.error_code == ErrorCode.NONE
            await sock.close()

        loop.run_until_complete(run())
