"""Tier-1 static-analysis gate + AST linter unit tests.

The gate half makes regressions CI failures: the custom AST linter
(fluvio_tpu/analysis/ast_lint.py) must run clean over the whole
package — an unpinned kernel literal, a host sync in a dispatch hot
path, an unguarded telemetry seam, a mutable default, or an unused
import anywhere in fluvio_tpu/ fails tier-1 — and ``ruff check`` (the
curated rule set in pyproject.toml) runs too when the binary exists.

The unit half pins each rule's detection on synthetic sources, so the
gate cannot silently weaken.
"""

from __future__ import annotations

import os
import shutil
import subprocess

import pytest

from fluvio_tpu.analysis.ast_lint import lint_repo, lint_source

_KERNEL_PATH = "fluvio_tpu/smartengine/tpu/pallas_kernels.py"
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# The gate
# ---------------------------------------------------------------------------


def test_repo_lint_is_clean():
    """The CI gate: the whole fluvio_tpu package passes the invariant
    linter. A regression anywhere — including a fresh unpinned weak
    literal in a kernel module — fails tier-1 here."""
    violations = lint_repo()
    assert not violations, "\n".join(str(v) for v in violations)


def test_ruff_clean_when_available():
    """`ruff check` over the curated pyproject rule set, wired into
    tier-1 wherever the binary exists (the native linter above keeps
    the same classes enforced where it does not)."""
    ruff = shutil.which("ruff")
    if ruff is None:
        pytest.skip("ruff not installed in this environment")
    proc = subprocess.run(
        [ruff, "check", "fluvio_tpu"],
        cwd=_REPO_ROOT,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_pyproject_carries_ruff_config():
    with open(os.path.join(_REPO_ROOT, "pyproject.toml")) as f:
        text = f.read()
    assert "[tool.ruff" in text
    assert "F401" in text and "B006" in text


def test_cli_full_analysis_gate_is_clean():
    """The CI deploy gate, all four repo passes through the one CLI the
    operator runs: `analyze --lint --concurrency --values --env` must
    exit 0 — AST invariants, lock discipline (FLV2xx), value flow
    (FLV3xx), and the env-config registry (FLV4xx)."""
    import json
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, "-m", "fluvio_tpu.cli",
         "analyze", "--lint", "--concurrency", "--values", "--env",
         "--format", "json"],
        cwd=_REPO_ROOT,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    # combined passes must emit ONE parseable document, not four
    # concatenated dumps
    doc = json.loads(proc.stdout)
    assert doc["lint"] == []
    assert doc["concurrency"]["cycles"] == []
    assert not [
        f for f in doc["concurrency"]["findings"] if f["level"] == "error"
    ]
    assert doc["values"]["findings"] == []
    assert doc["env"]["findings"] == []
    assert doc["env"]["registry"]["count"] >= 60


def test_valueflow_pass_clean_in_process():
    """Same gate without the subprocess: unsuppressed FLV3xx findings
    anywhere in the registered engine modules fail tier-1."""
    from fluvio_tpu.analysis import analyze_values

    report = analyze_values()
    assert not report.findings, "\n".join(str(f) for f in report.findings)


def test_env_lint_clean_in_process():
    """FLV401/402/403 over the package + README fail tier-1 here."""
    from fluvio_tpu.analysis import lint_env

    findings = lint_env()
    assert not findings, "\n".join(str(f) for f in findings)


def test_concurrency_pass_clean_in_process():
    """Same gate without the subprocess: ERROR-severity FLV2xx findings
    anywhere in fluvio_tpu/ fail tier-1."""
    from fluvio_tpu.analysis import analyze_concurrency

    report = analyze_concurrency()
    assert not report.errors(), "\n".join(str(f) for f in report.errors())


# ---------------------------------------------------------------------------
# FLV001/FLV002 — kernel literal pinning
# ---------------------------------------------------------------------------


def _codes(violations):
    return [v.code for v in violations]


def test_both_literal_where_flags_anywhere_in_kernel_module():
    src = (
        "import jax.numpy as jnp\n"
        "def helper(mask):\n"
        "    return jnp.where(mask, 1, 0)\n"
    )
    vs = lint_source(src, path=_KERNEL_PATH)
    assert "FLV001" in _codes(vs)


def test_single_literal_where_ok_outside_kernel_bodies():
    # a weak literal paired with an array operand defers to the array
    # dtype — only the both-literal form promotes
    src = (
        "import jax.numpy as jnp\n"
        "def helper(mask, x):\n"
        "    return jnp.where(mask, x, 0)\n"
    )
    assert not lint_source(src, path=_KERNEL_PATH)


def test_pinned_where_is_clean():
    src = (
        "import jax.numpy as jnp\n"
        "def _scan_kernel(ref):\n"
        "    return jnp.where(ref[0] > 0, jnp.int32(1), jnp.int32(0))\n"
    )
    assert not lint_source(src, path=_KERNEL_PATH)


def test_kernel_body_flags_any_bare_value_literal():
    src = (
        "import jax.numpy as jnp\n"
        "def _scan_kernel(ref, out):\n"
        "    out[:] = jnp.where(ref[0] > 0, 1, ref[1])\n"
    )
    vs = lint_source(src, path=_KERNEL_PATH)
    assert "FLV002" in _codes(vs)


def test_kernel_body_flags_bare_fori_bounds():
    src = (
        "import jax\n"
        "def _scan_kernel(ref):\n"
        "    return jax.lax.fori_loop(0, 8, lambda i, c: c, ref[0])\n"
    )
    vs = lint_source(src, path=_KERNEL_PATH)
    assert _codes(vs).count("FLV002") == 2  # both bounds


def test_kernel_body_flags_undtyped_full():
    src = (
        "import jax.numpy as jnp\n"
        "def _x_kernel(ref):\n"
        "    a = jnp.full((1, 8), 3)\n"
        "    b = jnp.full((1, 8), 3, dtype=jnp.int32)\n"
        "    return a, b\n"
    )
    vs = lint_source(src, path=_KERNEL_PATH)
    assert _codes(vs) == ["FLV002"]


def test_non_kernel_module_skips_kernel_rules():
    src = (
        "import jax.numpy as jnp\n"
        "def _scan_kernel(ref):\n"
        "    return jnp.where(ref[0] > 0, 1, ref[1])\n"
    )
    assert not lint_source(src, path="fluvio_tpu/telemetry/registry.py")


# ---------------------------------------------------------------------------
# FLV003 — host syncs
# ---------------------------------------------------------------------------


def test_host_sync_flags_in_kernel_module():
    src = (
        "def fetch(x):\n"
        "    n = x.item()\n"
        "    x.block_until_ready()\n"
        "    return n\n"
    )
    vs = lint_source(src, path=_KERNEL_PATH)
    assert _codes(vs) == ["FLV003", "FLV003"]


def test_host_sync_flags_in_executor_dispatch_side_only():
    exec_path = "fluvio_tpu/smartengine/tpu/executor.py"
    hot = (
        "import jax\n"
        "class E:\n"
        "    def _dispatch(self, buf):\n"
        "        return jax.device_get(buf)\n"
    )
    assert _codes(lint_source(hot, path=exec_path)) == ["FLV003"]
    fetch_side = (
        "import jax\n"
        "class E:\n"
        "    def _fetch(self, h):\n"
        "        return jax.device_get(h)\n"
    )
    assert not lint_source(fetch_side, path=exec_path)


# ---------------------------------------------------------------------------
# FLV004 — telemetry seams
# ---------------------------------------------------------------------------


def test_telemetry_seam_allows_guarded_api():
    src = (
        "from fluvio_tpu.telemetry import TELEMETRY\n"
        "def f(n):\n"
        "    if not TELEMETRY.enabled:\n"
        "        return\n"
        "    TELEMETRY.gauge_add('x', n)\n"
        "    TELEMETRY.add_spill('r')\n"
    )
    assert not lint_source(src, path="fluvio_tpu/smartengine/tpu/buffer.py")


def test_telemetry_seam_rejects_registry_internals():
    src = (
        "from fluvio_tpu.telemetry import TELEMETRY\n"
        "def f():\n"
        "    TELEMETRY.spans.push(None)\n"
        "    return TELEMETRY.gauges\n"
    )
    vs = lint_source(src, path="fluvio_tpu/smartengine/tpu/buffer.py")
    assert _codes(vs) == ["FLV004", "FLV004"]


# ---------------------------------------------------------------------------
# FLV101/FLV102 — hygiene
# ---------------------------------------------------------------------------


def test_mutable_default_flags():
    src = "def f(a, b=[], c={}, d=dict()):\n    return a\n"
    vs = lint_source(src, path="fluvio_tpu/x.py")
    assert _codes(vs) == ["FLV101", "FLV101", "FLV101"]


def test_unused_import_flags_and_noqa_suppresses():
    src = "import os\nimport sys  # noqa: F401\n"
    vs = lint_source(src, path="fluvio_tpu/x.py")
    assert len(vs) == 1 and vs[0].code == "FLV102"
    assert "os" in vs[0].message


def test_quoted_annotation_counts_as_use():
    src = (
        "from typing import List\n"
        "from foo import Bar\n"
        "def f(x: 'List[Bar]'):\n"
        "    return x\n"
    )
    assert not lint_source(src, path="fluvio_tpu/x.py")


def test_docstring_mention_does_not_mask_unused_import():
    src = '"""Uses Bar for things."""\nfrom foo import Bar\n'
    vs = lint_source(src, path="fluvio_tpu/x.py")
    assert _codes(vs) == ["FLV102"]


def test_init_py_exempt_from_unused_imports():
    src = "from foo import Bar\n"
    assert not lint_source(src, path="fluvio_tpu/sub/__init__.py")


def test_syntax_error_reports_flv000():
    vs = lint_source("def broken(:\n", path="fluvio_tpu/x.py")
    assert _codes(vs) == ["FLV000"]
