"""Storage tests against temp dirs (mirrors fluvio-storage replica tests)."""

import os

import pytest

from fluvio_tpu.protocol.error import FluvioError
from fluvio_tpu.protocol.record import Batch, Record, RecordSet
from fluvio_tpu.storage import Cleaner, FileReplica, ReplicaConfig
from fluvio_tpu.storage.replica import (
    ISOLATION_READ_COMMITTED,
    ISOLATION_READ_UNCOMMITTED,
)


def make_config(tmp_path, **kw) -> ReplicaConfig:
    return ReplicaConfig(base_dir=str(tmp_path), **kw)


def rs(*values, first_timestamp=None):
    return RecordSet().add(
        Batch.from_records(
            [Record(value=v) for v in values], first_timestamp=first_timestamp
        )
    )


class TestWriteRead:
    def test_roundtrip(self, tmp_path):
        replica = FileReplica("topic", 0, 0, make_config(tmp_path))
        replica.write_recordset(rs(b"a", b"b", b"c"))
        assert replica.get_leo() == 3
        assert replica.get_hw() == 0
        batches = replica.read_records(0, 1 << 20)
        assert [r.value for r in batches[0].memory_records()] == [b"a", b"b", b"c"]
        replica.close()

    def test_offsets_assigned_across_batches(self, tmp_path):
        replica = FileReplica("topic", 0, 0, make_config(tmp_path))
        replica.write_recordset(rs(b"a", b"b"))
        replica.write_recordset(rs(b"c"))
        assert replica.get_leo() == 3
        batches = replica.read_records(0, 1 << 20)
        assert batches[0].base_offset == 0
        assert batches[1].base_offset == 2
        replica.close()

    def test_read_from_mid_offset(self, tmp_path):
        replica = FileReplica("t", 0, 0, make_config(tmp_path))
        for i in range(5):
            replica.write_recordset(rs(f"rec-{i}".encode()))
        batches = replica.read_records(3, 1 << 20)
        assert batches[0].base_offset == 3
        replica.close()

    def test_max_bytes_bounds_slice(self, tmp_path):
        replica = FileReplica("t", 0, 0, make_config(tmp_path))
        for i in range(10):
            replica.write_recordset(rs(b"x" * 200))
        one_batch = replica.read_records(0, 300)
        assert len(one_batch) == 1
        replica.close()

    def test_offset_out_of_range(self, tmp_path):
        replica = FileReplica("t", 0, 0, make_config(tmp_path))
        replica.write_recordset(rs(b"a"))
        with pytest.raises(FluvioError):
            replica.read_partition_slice(99, 1 << 20)
        replica.close()


class TestIsolation:
    def test_read_committed_bounded_by_hw(self, tmp_path):
        replica = FileReplica("t", 0, 0, make_config(tmp_path))
        replica.write_recordset(rs(b"a", b"b"))
        # hw still 0: committed read sees nothing
        sl = replica.read_partition_slice(0, 1 << 20, ISOLATION_READ_COMMITTED)
        assert sl.file_slice is None
        replica.update_high_watermark(2)
        batches = replica.read_records(0, 1 << 20, ISOLATION_READ_COMMITTED)
        assert batches and batches[0].records_len() == 2
        replica.close()

    def test_hw_cannot_exceed_leo(self, tmp_path):
        replica = FileReplica("t", 0, 0, make_config(tmp_path))
        with pytest.raises(FluvioError):
            replica.update_high_watermark(5)
        replica.close()


class TestReload:
    def test_reload_preserves_log_and_hw(self, tmp_path):
        config = make_config(tmp_path)
        replica = FileReplica("t", 0, 0, config)
        replica.write_recordset(rs(b"a", b"b"), update_highwatermark=True)
        replica.close()

        again = FileReplica("t", 0, 0, config)
        assert again.get_leo() == 2
        assert again.get_hw() == 2
        batches = again.read_records(0, 1 << 20)
        assert [r.value for r in batches[0].memory_records()] == [b"a", b"b"]
        again.write_recordset(rs(b"c"))
        assert again.get_leo() == 3
        again.close()

    def test_torn_tail_truncated(self, tmp_path):
        config = make_config(tmp_path)
        replica = FileReplica("t", 0, 0, config)
        replica.write_recordset(rs(b"a"))
        replica.write_recordset(rs(b"b"))
        log_path = replica.active_segment.log_path
        replica.close()
        # corrupt: append garbage partial batch
        with open(log_path, "ab") as f:
            f.write(b"\x00\x00\x00\x00\x00\x00\x00\x09\x00\x00\x01\x00garbage")
        again = FileReplica("t", 0, 0, config)
        assert again.get_leo() == 2
        # the log is usable after repair
        again.write_recordset(rs(b"c"))
        assert [b.base_offset for b in again.read_records(0, 1 << 20)] == [0, 1, 2]
        again.close()


class TestSegmentRolling:
    def test_rolls_and_reads_across_segments(self, tmp_path):
        config = make_config(tmp_path, segment_max_bytes=500)
        replica = FileReplica("t", 0, 0, config)
        for i in range(10):
            replica.write_recordset(rs(f"value-{i:04d}".encode() * 10))
        assert len(replica.prev_segments) > 0
        # every offset readable
        for off in range(10):
            batches = replica.read_records(off, 1 << 20)
            assert batches[0].base_offset == off
        replica.close()

    def test_reload_multi_segment(self, tmp_path):
        config = make_config(tmp_path, segment_max_bytes=400)
        replica = FileReplica("t", 0, 0, config)
        for i in range(8):
            replica.write_recordset(rs(b"z" * 100))
        n_prev = len(replica.prev_segments)
        leo = replica.get_leo()
        replica.close()
        again = FileReplica("t", 0, 0, config)
        assert again.get_leo() == leo
        assert len(again.prev_segments) == n_prev
        assert again.read_records(0, 1 << 20)[0].base_offset == 0
        again.close()


class TestLookback:
    def test_read_last_records(self, tmp_path):
        replica = FileReplica("t", 0, 0, make_config(tmp_path))
        for i in range(6):
            replica.write_recordset(rs(f"{i}".encode()), update_highwatermark=True)
        last = replica.read_last_records(3)
        assert [r.value for r in last] == [b"3", b"4", b"5"]
        replica.close()

    def test_read_last_records_age_bound(self, tmp_path):
        """Lookback::Age{age, last}: drop records older than the floor."""
        replica = FileReplica("t", 0, 0, make_config(tmp_path))
        replica.write_recordset(rs(b"old-1", b"old-2", first_timestamp=1_000))
        replica.write_recordset(rs(b"new-1", b"new-2", first_timestamp=5_000))
        replica.update_high_watermark_to_end()
        # age-only (last=0): everything at/after the floor
        assert [
            r.value for r in replica.read_last_records(0, min_timestamp=5_000)
        ] == [b"new-1", b"new-2"]
        # age + last cap
        assert [
            r.value for r in replica.read_last_records(1, min_timestamp=5_000)
        ] == [b"new-2"]
        # floor before everything: age bound admits all, count caps
        assert [
            r.value for r in replica.read_last_records(3, min_timestamp=0)
        ] == [b"old-2", b"new-1", b"new-2"]
        replica.close()


class TestCleaner:
    def test_age_retention(self, tmp_path):
        config = make_config(tmp_path, segment_max_bytes=300, retention_seconds=10)
        replica = FileReplica("t", 0, 0, config)
        old_ts = 1_000_000
        for i in range(6):
            replica.write_recordset(rs(b"x" * 100, first_timestamp=old_ts))
        assert replica.prev_segments
        removed = Cleaner(replica).clean(now_ms=old_ts + 60_000)
        assert removed
        assert replica.get_log_start_offset() > 0
        replica.close()

    def test_size_retention(self, tmp_path):
        config = make_config(
            tmp_path, segment_max_bytes=300, max_partition_size=600,
            retention_seconds=10**9,
        )
        replica = FileReplica("t", 0, 0, config)
        for i in range(8):
            replica.write_recordset(rs(b"y" * 100))
        removed = Cleaner(replica).clean()
        assert removed
        replica.close()

    def test_start_offset_errors_after_clean(self, tmp_path):
        config = make_config(tmp_path, segment_max_bytes=300, retention_seconds=10)
        replica = FileReplica("t", 0, 0, config)
        for i in range(6):
            replica.write_recordset(rs(b"x" * 100, first_timestamp=1000))
        Cleaner(replica).clean(now_ms=10_000_000)
        start = replica.get_log_start_offset()
        with pytest.raises(FluvioError):
            replica.read_partition_slice(0, 1 << 20)
        assert replica.read_records(start, 1 << 20)
        replica.close()


class TestRemove:
    def test_remove_deletes_directory(self, tmp_path):
        replica = FileReplica("t", 1, 0, make_config(tmp_path))
        replica.write_recordset(rs(b"a"))
        d = replica.directory
        assert os.path.exists(d)
        replica.remove()
        assert not os.path.exists(d)


class TestIndexReload:
    def test_index_survives_reload_and_stays_monotonic(self, tmp_path):
        # regression: entry 0 indexes log position 0; reload must neither
        # wipe the index nor resurrect stale non-monotonic entries
        config = make_config(tmp_path, index_max_interval_bytes=1)
        replica = FileReplica("t", 0, 0, config)
        for i in range(5):
            replica.write_recordset(rs(f"{i}".encode()))
        n = len(replica.active_segment.index)
        assert n >= 5
        replica.close()
        again = FileReplica("t", 0, 0, config)
        assert len(again.active_segment.index) == n
        again.write_recordset(rs(b"5"))
        again.close()
        final = FileReplica("t", 0, 0, config)
        for off in range(6):
            assert final.read_records(off, 1 << 20)[0].base_offset == off
        final.close()


class TestLookbackAcrossSegments:
    def test_read_last_records_spans_segments(self, tmp_path):
        config = make_config(tmp_path, segment_max_bytes=300)
        replica = FileReplica("t", 0, 0, config)
        for i in range(10):
            replica.write_recordset(
                rs(f"v-{i:03d}".encode() * 5), update_highwatermark=True
            )
        assert replica.prev_segments  # must actually have rolled
        last = replica.read_last_records(8)
        assert len(last) == 8
        assert last[-1].value.startswith(b"v-009")
        replica.close()
