"""Stream-model + metadata store tests.

Mirrors fluvio-stream-model's dual_epoch_map tests and the
stream-dispatcher local-backend behavior: epoch fencing semantics,
listener wakeups, write-intent flow to the YAML backend, resync.
"""

import asyncio

import pytest

from fluvio_tpu.metadata import (
    SmartModuleSpec,
    SpuSpec,
    TopicResolution,
    TopicSpec,
    TopicStatus,
)
from fluvio_tpu.metadata.client import InMemoryMetadataClient, LocalMetadataClient
from fluvio_tpu.metadata.dispatcher import MetadataDispatcher
from fluvio_tpu.stream_model import (
    DualEpochMap,
    LocalStore,
    MetadataStoreObject,
    StoreContext,
)


def topic_obj(key: str, partitions: int = 1) -> MetadataStoreObject:
    return MetadataStoreObject(key=key, spec=TopicSpec.computed(partitions))


class TestDualEpochMap:
    def test_apply_bumps_epoch_and_revision(self):
        m = DualEpochMap()
        assert m.epoch == 0
        assert m.apply(topic_obj("a"))
        assert m.epoch == 1
        assert m.get("a").revision == 0
        # identical re-apply is a no-op
        assert not m.apply(topic_obj("a"))
        assert m.epoch == 1
        # changed spec bumps both
        assert m.apply(topic_obj("a", partitions=2))
        assert m.epoch == 2
        assert m.get("a").revision == 1

    def test_changes_since_spec_vs_status(self):
        m = DualEpochMap()
        m.apply(topic_obj("a"))
        e1 = m.epoch
        m.update_status("a", TopicStatus(resolution=TopicResolution.PROVISIONED))
        spec_changes = m.changes_since(e1, "spec")
        status_changes = m.changes_since(e1, "status")
        assert spec_changes.updates == []
        assert [o.key for o in status_changes.updates] == ["a"]

    def test_deletes_and_full_resync_fence(self):
        m = DualEpochMap()
        m.apply(topic_obj("a"))
        m.apply(topic_obj("b"))
        e = m.epoch
        m.delete("a")
        changes = m.changes_since(e)
        assert changes.deletes == ["a"]
        assert not changes.is_sync_all
        # prune past the deletion: older listeners get full resync
        m.prune_deletions(m.epoch)
        stale = m.changes_since(e)
        assert stale.is_sync_all
        assert [o.key for o in stale.updates] == ["b"]

    def test_sync_all_deletes_absent(self):
        m = DualEpochMap()
        m.apply(topic_obj("a"))
        m.apply(topic_obj("b"))
        m.sync_all([topic_obj("b"), topic_obj("c")])
        assert sorted(m.keys()) == ["b", "c"]


class TestLocalStore:
    def test_listener_wakes_on_change(self):
        async def run():
            store = LocalStore(TopicSpec)
            listener = store.change_listener()
            assert listener.sync_changes().is_sync_all  # initial full sync
            got = []

            async def wait_change():
                await listener.listen()
                got.extend(o.key for o in listener.sync_changes().updates)

            task = asyncio.ensure_future(wait_change())
            await asyncio.sleep(0.01)
            store.apply(topic_obj("t1"))
            await asyncio.wait_for(task, 2)
            assert got == ["t1"]

        asyncio.run(run())

    def test_wait_action_resolves_on_status(self):
        async def run():
            ctx = StoreContext(TopicSpec)
            await ctx.apply(topic_obj("t"))

            async def provision():
                await asyncio.sleep(0.02)
                await ctx.update_status(
                    "t", TopicStatus(resolution=TopicResolution.PROVISIONED)
                )

            asyncio.ensure_future(provision())
            obj = await ctx.wait_action(
                "t",
                lambda o: o is not None
                and o.status.resolution == TopicResolution.PROVISIONED,
                timeout=2,
            )
            assert obj.status.resolution == TopicResolution.PROVISIONED

        asyncio.run(run())


class TestLocalMetadataClient:
    def test_yaml_roundtrip(self, tmp_path):
        async def run():
            client = LocalMetadataClient(str(tmp_path))
            await client.apply(topic_obj("events", partitions=3))
            await client.apply(
                MetadataStoreObject(
                    key="filt",
                    spec=SmartModuleSpec.from_source(b"x = 1", "filt"),
                )
            )
            topics = await client.retrieve_items(TopicSpec)
            assert len(topics) == 1
            assert topics[0].spec.replicas.partitions == 3
            sms = await client.retrieve_items(SmartModuleSpec)
            assert sms[0].spec.artifact.payload == b"x = 1"
            await client.delete_item(TopicSpec, "events")
            assert await client.retrieve_items(TopicSpec) == []

        asyncio.run(run())

    def test_watch_detects_writes(self, tmp_path):
        async def run():
            client = LocalMetadataClient(str(tmp_path))
            await client.watch_changed(TopicSpec, 0.01)  # prime mtime
            changed = await client.watch_changed(TopicSpec, 0.05)
            assert not changed
            await client.apply(topic_obj("t"))
            assert await client.watch_changed(TopicSpec, 1.0)

        asyncio.run(run())


class TestDispatcher:
    def test_resync_and_writeback(self, tmp_path):
        async def run():
            client = LocalMetadataClient(str(tmp_path))
            await client.apply(topic_obj("pre-existing"))
            ctx = StoreContext(TopicSpec)
            dispatcher = MetadataDispatcher(client, ctx, reconcile_interval=60)
            dispatcher.start()
            # startup resync pulls the pre-existing object
            obj = await ctx.wait_action(
                "pre-existing", lambda o: o is not None, timeout=2
            )
            assert obj is not None
            # controller-side apply flows back to the YAML backend
            await ctx.apply(topic_obj("fresh"))
            for _ in range(100):
                if any(
                    o.key == "fresh" for o in await client.retrieve_items(TopicSpec)
                ):
                    break
                await asyncio.sleep(0.02)
            else:
                raise AssertionError("write-intent never reached backend")
            await dispatcher.stop()

        asyncio.run(run())

    def test_external_change_propagates(self, tmp_path):
        async def run():
            client = LocalMetadataClient(str(tmp_path))
            ctx = StoreContext(SpuSpec)
            dispatcher = MetadataDispatcher(client, ctx, reconcile_interval=60)
            dispatcher.start()
            await asyncio.sleep(0.05)
            # an "external" writer (another process) adds an object
            other = LocalMetadataClient(str(tmp_path))
            await other.apply(
                MetadataStoreObject(key="5001", spec=SpuSpec(id=5001))
            )
            obj = await ctx.wait_action("5001", lambda o: o is not None, timeout=3)
            assert obj is not None and obj.spec.id == 5001
            await dispatcher.stop()

        asyncio.run(run())
