"""Striped wide-record layout (smartengine/tpu/stripes.py).

Records wider than the narrow layout stage as K consecutive device rows
sharing a segment id; these tests pin bit-equality against the
interpreting backend for every stripeable stage family — filter
(boundary-straddling literals included), postop maps, split explodes
with elements spanning stripes, and aggregate chains — on the
single-device AND sharded engine modes, plus the graceful interpreter
spill for chains outside the stripeable subset. Stripe geometry shrinks
via env overrides so small corpora exercise multi-stripe segments.
"""

from __future__ import annotations

import jax
import numpy as np
import pytest

from fluvio_tpu.models import lookup
from fluvio_tpu.protocol.record import Record
from fluvio_tpu.smartengine import SmartEngine, SmartModuleConfig
from fluvio_tpu.smartengine.tpu import stripes
from fluvio_tpu.smartengine.tpu.buffer import MAX_WIDTH, RecordBuffer
from fluvio_tpu.smartmodule import SmartModuleInput, dsl
from fluvio_tpu.smartmodule.sdk import SmartModuleDef
from fluvio_tpu.smartmodule.types import SmartModuleKind

# 64-byte stripes, 16-byte overlap -> 48-byte step: a ~200-byte record
# spans 4-5 stripes, and literals placed near multiples of 48 straddle
# stripe boundaries (the overlap-containment property under test)
STRIPE_ENV = {
    "FLUVIO_STRIPE_THRESHOLD": "64",
    "FLUVIO_STRIPE_WIDTH": "64",
    "FLUVIO_STRIPE_OVERLAP": "16",
}


@pytest.fixture
def small_stripes(monkeypatch):
    for k, v in STRIPE_ENV.items():
        monkeypatch.setenv(k, v)


def upper_map_module() -> SmartModuleDef:
    m = SmartModuleDef(name="upper-map")
    m.dsl[SmartModuleKind.MAP] = dsl.MapProgram(value=dsl.Upper(arg=dsl.Value()))
    m.hooks[SmartModuleKind.MAP] = lambda record: dsl.ascii_upper(record.value)
    return m


def filter_module(pattern: str) -> SmartModuleDef:
    m = SmartModuleDef(name="stripe-filter")
    m.dsl[SmartModuleKind.FILTER] = dsl.FilterProgram(
        predicate=dsl.RegexMatch(arg=dsl.Value(), pattern=pattern)
    )
    return m


def predicate_module(predicate: dsl.Expr) -> SmartModuleDef:
    m = SmartModuleDef(name="stripe-predicate")
    m.dsl[SmartModuleKind.FILTER] = dsl.FilterProgram(predicate=predicate)
    return m


def split_module(sep: bytes = b",") -> SmartModuleDef:
    m = SmartModuleDef(name="stripe-split")
    m.dsl[SmartModuleKind.ARRAY_MAP] = dsl.ArrayMapProgram(mode="split", sep=sep)
    m.hooks[SmartModuleKind.ARRAY_MAP] = lambda record: [
        x for x in record.value.split(sep) if x
    ]
    return m


def _build(backend: str, mods, mesh=None):
    eng = (
        SmartEngine(backend=backend, mesh_devices=mesh)
        if mesh
        else SmartEngine(backend=backend)
    )
    b = eng.builder()
    for mod, params in mods:
        b.add_smart_module(SmartModuleConfig(params=params or {}), mod)
    return b.initialize()


def _run(chain, vals, ts=None):
    records = [Record(value=v) for v in vals]
    for i, r in enumerate(records):
        r.offset_delta = i
        if ts is not None:
            r.timestamp_delta = int(ts[i])
    out = chain.process(SmartModuleInput.from_records(records, 0, 1_000_000))
    assert out.error is None, out.error
    return [(r.value, r.key, r.offset_delta) for r in out.successes]


def _assert_equivalent(mods_factory, vals, ts=None, mesh=None, striped=True):
    chain = _build("tpu", mods_factory(), mesh=mesh)
    assert chain.backend_in_use == "tpu"
    ex = chain.tpu_chain
    assert (ex._striped_chain() is not None) == striped
    got = _run(chain, vals, ts)
    ref = _run(_build("python", mods_factory()), vals, ts)
    assert got == ref
    return ex


def _wide_corpus(n=300, seed=7):
    rng = np.random.default_rng(seed)
    names = ["fluvio", "kafka", "pulsar", "fluvio-tpu", "redpanda"]
    return [
        (
            f"{'pre' * int(rng.integers(0, 60))} "
            f"{names[int(rng.integers(0, 5))]} tail{i}"
        ).encode()
        for i in range(n)
    ]


class TestStripePlan:
    def test_device_plan_matches_host_counts(self):
        rng = np.random.default_rng(11)
        s, v = 64, 16
        lengths = np.concatenate(
            [rng.integers(0, 400, size=60), [0, 1, v, v + 1, s, s + 1, 2 * s]]
        ).astype(np.int32)
        count = len(lengths) - 3  # tail rows are padding
        k_host = stripes.stripe_counts(lengths[:count], s, v)
        r = int(k_host.sum())
        import jax.numpy as jnp

        live = jnp.arange(len(lengths)) < count
        plan = jax.jit(
            lambda l: stripes.plan_device(l, live, r + 5, s, v)
        )(jnp.asarray(lengths))
        k_dev = np.asarray(plan["k"])
        assert np.array_equal(k_dev[:count], k_host)
        assert k_dev[count:].sum() == 0
        # every live stripe row reconstructs its segment's coverage
        seg = np.asarray(plan["seg"])
        idx = np.asarray(plan["stripe_idx"])
        slen = np.asarray(plan["stripe_len"])
        astart = np.asarray(plan["abs_start"])
        row_live = np.asarray(plan["row_live"])
        step = s - v
        for i in range(count):
            rows = np.flatnonzero((seg == i) & row_live)
            assert len(rows) == k_host[i]
            assert np.array_equal(idx[rows], np.arange(k_host[i]))
            assert np.array_equal(astart[rows], np.arange(k_host[i]) * step)
            # stripes cover the record exactly: last stripe ends at len
            if len(rows):
                last = rows[-1]
                assert astart[last] + slen[last] == lengths[i]

    def test_bad_stripe_params_rejected(self, monkeypatch):
        monkeypatch.setenv("FLUVIO_STRIPE_WIDTH", "64")
        monkeypatch.setenv("FLUVIO_STRIPE_OVERLAP", "64")
        with pytest.raises(ValueError):
            stripes.stripe_params()

    def test_defaults_word_aligned(self):
        s, v = stripes.stripe_params()
        assert s % 4 == 0 and v % 4 == 0 and v < s


class TestStripedEquivalence:
    def test_filter_literal_straddles_boundaries(self, small_stripes):
        # place the literal at every offset around the 48-byte stripe
        # step so matches cross stripe boundaries in both directions
        vals = [
            (b"x" * pad) + b"fluvio" + b"y" * 40 for pad in range(0, 120)
        ] + [b"x" * pad + b"nope" + b"y" * 40 for pad in range(0, 60)]
        ex = _assert_equivalent(
            lambda: [(filter_module("fluvio"), None)], vals
        )
        assert ex._needs_stripes is not None

    def test_filter_boolean_composition(self, small_stripes):
        pred = dsl.And(
            args=[
                dsl.Or(
                    args=[
                        dsl.Contains(arg=dsl.Value(), literal=b"fluvio"),
                        dsl.Contains(arg=dsl.Value(), literal=b"pulsar"),
                    ]
                ),
                dsl.Not(arg=dsl.Contains(arg=dsl.Value(), literal=b"veto")),
                dsl.Cmp(cmp="gt", left=dsl.Len(arg=dsl.Value()),
                        right=dsl.ParseInt(arg=dsl.Const(data=b"60"))),
            ]
        )
        rng = np.random.default_rng(3)
        words = [b"fluvio", b"pulsar", b"veto", b"other"]
        vals = [
            b" ".join(
                words[int(rng.integers(0, 4))]
                for _ in range(int(rng.integers(1, 40)))
            )
            for _ in range(250)
        ]
        _assert_equivalent(lambda: [(predicate_module(pred), None)], vals)

    def test_filter_anchored_literals(self, small_stripes):
        vals = (
            [b"fluvio" + b"x" * n for n in (0, 10, 50, 100, 150)]
            + [b"x" * n + b"fluvio" for n in (0, 10, 47, 48, 100, 141)]
            + [b"fluvio", b"x" * 200, b"fluvioz" * 25]
        )
        for pattern in ("^fluvio", "fluvio$", "^fluvio$"):
            _assert_equivalent(
                lambda: [(filter_module(pattern), None)], vals
            )

    def test_filter_plus_upper_map(self, small_stripes):
        vals = _wide_corpus()
        _assert_equivalent(
            lambda: [
                (filter_module("fluvio"), None),
                (upper_map_module(), None),
            ],
            vals,
        )

    def test_upper_map_then_filter_sees_folded_bytes(self, small_stripes):
        # the filter's striped kernels must search the POST-fold bytes
        vals = [b"a" * n + b"FLUVIO" + b"b" * 30 for n in range(0, 100, 7)]
        _assert_equivalent(
            lambda: [
                (upper_map_module(), None),
                (filter_module("FLUVIO"), None),
            ],
            vals,
        )
        # lowercase pattern must now never match after the fold
        _assert_equivalent(
            lambda: [
                (upper_map_module(), None),
                (filter_module("fluvio"), None),
            ],
            vals,
        )

    def test_aggregate_sum_max_count(self, small_stripes):
        rng = np.random.default_rng(5)
        vals = [
            (f"{int(rng.integers(-500, 1000))} {'x' * int(rng.integers(0, 200))}").encode()
            for _ in range(300)
        ]
        for name in ("aggregate-sum", "aggregate-max", "aggregate-count"):
            _assert_equivalent(lambda: [(lookup(name), None)], vals)

    def test_filter_then_aggregate_carries_across_calls(self, small_stripes):
        rng = np.random.default_rng(9)
        vals = [
            (f"{int(rng.integers(0, 100))} {'fluvio ' * int(rng.integers(0, 30))}").encode()
            for _ in range(200)
        ]
        mods = lambda: [
            (filter_module("fluvio"), None),
            (lookup("aggregate-sum"), None),
        ]
        tpu, py = _build("tpu", mods()), _build("python", mods())
        assert tpu.tpu_chain._striped_chain() is not None
        for lo in (0, 100):  # stateful: two process() calls chain carries
            got = _run(tpu, vals[lo : lo + 100])
            ref = _run(py, vals[lo : lo + 100])
            assert got == ref

    def test_windowed_sum(self, small_stripes):
        rng = np.random.default_rng(13)
        vals = [
            (f"{int(rng.integers(0, 100))}{' ' * int(rng.integers(0, 150))}").encode()
            for _ in range(300)
        ]
        ts = (np.arange(300, dtype=np.int64) * 7919) % 60_000
        _assert_equivalent(
            lambda: [
                (lookup("windowed-sum"), {"kind": "sum_int", "window_ms": "1000"})
            ],
            vals,
            ts=ts,
        )

    def test_split_explode_elements_span_stripes(self, small_stripes):
        rng = np.random.default_rng(17)
        vals = []
        for i in range(250):
            parts = [
                b"e%d" % j + b"y" * int(rng.integers(0, 70))
                for j in range(int(rng.integers(1, 8)))
            ]
            v = b",".join(parts)
            if i % 3 == 0:
                v = b"," + v + b",,"  # leading/trailing/empty segments
            vals.append(v)
        vals += [b"", b",", b",,,", b"single" * 40]
        _assert_equivalent(lambda: [(split_module(), None)], vals)

    def test_filter_then_split_explode(self, small_stripes):
        rng = np.random.default_rng(19)
        vals = [
            (b"fluvio," if i % 2 else b"kafka,")
            + b",".join(
                b"p%d" % j + b"z" * int(rng.integers(0, 60))
                for j in range(int(rng.integers(1, 6)))
            )
            for i in range(200)
        ]
        _assert_equivalent(
            lambda: [
                (filter_module("fluvio"), None),
                (split_module(), None),
            ],
            vals,
        )

    def test_json_chain_runs_striped(self, small_stripes):
        # the headline regex-filter + json-map chain stripes at width:
        # the JsonGet structural machine carries state across stripes
        # (striped_json_span) and ships view descriptors
        vals = [
            (f'{{"name":"fluvio-{i}","pad":"{"x" * 120}"}}').encode()
            for i in range(60)
        ]
        ex = _assert_equivalent(
            lambda: [
                (lookup("regex-filter"), {"regex": "fluvio"}),
                (lookup("json-map"), {"field": "name"}),
            ],
            vals,
        )
        assert ex._striped_chain().has_span

    def test_json_sourced_literal_predicate_runs_striped(self, small_stripes):
        # ISSUE-11: JsonGet-sourced LITERAL predicates joined the
        # stripeable subset — the cross-stripe span machine resolves the
        # field's absolute span and a windowed compare matches inside
        # it. Fields before/after stripe joints, missing fields, and
        # decoys in OTHER fields must all verdict exactly.
        pred = dsl.Contains(
            arg=dsl.JsonGet(arg=dsl.Value(), key="name"), literal=b"fluvio"
        )
        pad = "x" * 120
        vals = []
        for i in range(60):
            if i % 4 == 0:
                # decoy: the literal appears OUTSIDE the extracted field
                vals.append(
                    f'{{"other":"fluvio","pad":"{pad}","name":"kafka"}}'.encode()
                )
            elif i % 4 == 1:
                # field starts past stripe 0 (the pad pushes it right)
                vals.append(f'{{"pad":"{pad}","name":"fluvio-{i}"}}'.encode())
            elif i % 4 == 2:
                # field value itself straddles stripe joints
                vals.append(
                    f'{{"name":"{"z" * 70}fluvio{"z" * 70}"}}'.encode()
                )
            else:
                vals.append(f'{{"name":"fluvio-{i}","pad":"{pad}"}}'.encode())
        ex = _assert_equivalent(
            lambda: [(predicate_module(pred), None)], vals
        )
        sc = ex._striped_chain()
        assert sc.has_json_pred and not sc.has_span and sc.needs_kmax
        # the kmax compile-shape axis sizes for json predicates too
        buf = RecordBuffer.from_records(
            [Record(value=vals[0], offset_delta=0)]
        )
        assert ex._stripe_kmax(buf) > 0

    def test_json_sourced_anchored_predicates_run_striped(self, small_stripes):
        pad = "x" * 110
        vals = [
            f'{{"name":"fluvio-{i}","pad":"{pad}"}}'.encode()
            for i in range(24)
        ] + [
            f'{{"pad":"{pad}","name":"tail-fluvio"}}'.encode()
            for i in range(24)
        ]
        for pred in (
            dsl.StartsWith(
                arg=dsl.JsonGet(arg=dsl.Value(), key="name"),
                literal=b"fluvio",
            ),
            dsl.EndsWith(
                arg=dsl.JsonGet(arg=dsl.Value(), key="name"),
                literal=b"fluvio",
            ),
            dsl.RegexMatch(
                arg=dsl.JsonGet(arg=dsl.Value(), key="name"),
                pattern="^fluvio",
            ),
        ):
            _assert_equivalent(
                lambda p=pred: [(predicate_module(p), None)], vals
            )

    def test_json_sourced_empty_anchored_regex_exact(self, small_stripes):
        # review regression: ^$ over a JsonGet source reduces to the
        # empty "equals" literal — it must match ONLY empty/missing
        # fields, not every record (the k==0 fast path must still
        # apply the length pin)
        pad = "x" * 110
        vals = [
            f'{{"name":"fluvio-{i}","pad":"{pad}"}}'.encode()
            for i in range(12)
        ] + [
            f'{{"name":"","pad":"{pad}"}}'.encode() for _ in range(6)
        ] + [
            f'{{"other":"y","pad":"{pad}"}}'.encode() for _ in range(6)
        ]
        pred = dsl.RegexMatch(
            arg=dsl.JsonGet(arg=dsl.Value(), key="name"), pattern="^$"
        )
        _assert_equivalent(lambda: [(predicate_module(pred), None)], vals)

    def test_json_pred_after_postop_map_rebinds_span_cache(
        self, small_stripes
    ):
        # review regression: the ctx span cache pins the source array
        # by identity — a postop stage between build and the predicate
        # rebinds ctx["sv"], and the predicate must read the FOLDED
        # bytes (parity with the reference engine), never a stale span
        pad = "x" * 110
        vals = [
            f'{{"name":"fluvio-{i}","pad":"{pad}"}}'.encode()
            for i in range(24)
        ]
        pred = dsl.Contains(
            arg=dsl.JsonGet(arg=dsl.Value(), key="NAME"), literal=b"FLUVIO"
        )
        _assert_equivalent(
            lambda: [
                (upper_map_module(), None),
                (predicate_module(pred), None),
            ],
            vals,
        )

    def test_json_sourced_regex_predicate_runs_striped(self, small_stripes):
        # ISSUE-16 flipped this boundary: a real DFA over an extracted
        # sub-span now lowers striped (stripes.striped_dfa_in_span)
        pred = dsl.RegexMatch(
            arg=dsl.JsonGet(arg=dsl.Value(), key="name"), pattern="cat|dog"
        )
        vals = [
            (
                f'{{"name":"{"cat" if i % 3 else "bird"}-{i}",'
                f'"pad":"{"x" * 120}"}}'
            ).encode()
            for i in range(40)
        ]
        _assert_equivalent(
            lambda: [(predicate_module(pred), None)], vals, striped=True
        )

    def test_literal_longer_than_overlap_runs_striped(self, small_stripes):
        # ISSUE-16 flipped this boundary: a literal that outgrows the
        # stripe overlap chains across stripes as a DFA now instead of
        # spilling to the interpreter.
        lit = b"q" * 20  # > 16-byte overlap: windowed match insufficient
        vals = [b"x" * n + lit + b"y" * 30 for n in range(0, 90, 5)]
        vals += [b"x" * n + b"q" * 19 + b"y" * 30 for n in range(0, 90, 10)]
        _assert_equivalent(
            lambda: [
                (predicate_module(dsl.Contains(arg=dsl.Value(), literal=lit)), None)
            ],
            vals,
            striped=True,
        )

    def test_word_count_spills(self, small_stripes):
        vals = [b"a b c " * 30 for _ in range(40)]
        _assert_equivalent(
            lambda: [(lookup("word-count"), None)], vals, striped=False
        )


@pytest.mark.skipif(
    len(jax.devices()) < 4, reason="needs 4 virtual devices"
)
class TestStripedSharded:
    def test_sharded_filter_map(self, small_stripes):
        vals = _wide_corpus(n=400)
        ex = _assert_equivalent(
            lambda: [
                (filter_module("fluvio"), None),
                (upper_map_module(), None),
            ],
            vals,
            mesh=4,
        )
        assert ex._sharded is not None

    def test_sharded_aggregate(self, small_stripes):
        rng = np.random.default_rng(23)
        vals = [
            (f"{int(rng.integers(0, 1000))} {'x' * int(rng.integers(0, 180))}").encode()
            for _ in range(400)
        ]
        _assert_equivalent(lambda: [(lookup("aggregate-sum"), None)], vals, mesh=4)

    def test_sharded_windowed(self, small_stripes):
        rng = np.random.default_rng(29)
        vals = [
            (f"{int(rng.integers(0, 100))}{' ' * int(rng.integers(0, 120))}").encode()
            for _ in range(400)
        ]
        ts = (np.arange(400, dtype=np.int64) * 7919) % 60_000
        _assert_equivalent(
            lambda: [
                (lookup("windowed-sum"), {"kind": "sum_int", "window_ms": "1000"})
            ],
            vals,
            ts=ts,
            mesh=4,
        )

    def test_sharded_fanout_wide_spills(self, small_stripes):
        # striped fan-out stays single-device; the sharded engine spills
        # wide explode batches to the interpreter instead of diverging
        vals = [b"a" * 100 + b",b,c" for _ in range(64)]
        _assert_equivalent(
            lambda: [(split_module(), None)], vals, mesh=4, striped=True
        )


class TestWideDefaults:
    def test_70k_records_fused_under_default_geometry(self):
        # the real thing: >64 KiB records through the DEFAULT stripe
        # params run fused (process_buffer raises TpuSpill if any spill
        # were left) and match the interpreter byte-for-byte
        body = b"x" * (70 * 1024)
        vals = [
            b'{"name":"%s-%d","body":"%s"}'
            % (b"fluvio" if i % 2 else b"kafka", i, body)
            for i in range(12)
        ]
        mods = lambda: [(filter_module("fluvio"), None)]
        chain = _build("tpu", mods())
        ex = chain.tpu_chain
        assert ex._striped_chain() is not None
        records = [Record(value=v) for v in vals]
        for i, r in enumerate(records):
            r.offset_delta = i
        buf = RecordBuffer.from_smartmodule_input(
            SmartModuleInput.from_records(records)
        )
        assert buf.width > MAX_WIDTH  # narrow layout cannot hold these
        assert ex._needs_stripes(buf)
        out = ex.process_buffer(buf)  # TpuSpill here would fail the test
        got = [(r.value, r.offset_delta) for r in out.to_records()]
        ref = [(v, o) for (v, _k, o) in _run(_build("python", mods()), vals)]
        assert got == ref

    def test_max_stageable_width_reflects_chain(self):
        from fluvio_tpu.smartengine.tpu.buffer import MAX_RECORD_WIDTH

        striped = _build(
            "tpu", [(filter_module("fluvio"), None)]
        ).tpu_chain
        assert striped.max_stageable_width() == MAX_RECORD_WIDTH
        # the headline json chain now stripes too (cross-stripe JsonGet)
        json_chain = _build(
            "tpu",
            [
                (lookup("regex-filter"), {"regex": "fluvio"}),
                (lookup("json-map"), {"field": "name"}),
            ],
        ).tpu_chain
        assert json_chain.max_stageable_width() == MAX_RECORD_WIDTH
        unstripeable = _build(
            "tpu", [(lookup("word-count"), None)]
        ).tpu_chain
        assert unstripeable.max_stageable_width() == MAX_WIDTH

    @pytest.mark.skipif(
        len(jax.devices()) < 4, reason="needs 4 virtual devices"
    )
    def test_sharded_fanout_width_guard_is_conservative(self):
        # sharded fan-out cannot stripe: the broker's pre-dispatch guard
        # must see the NARROW bound, or a wide slice would pass the
        # guard and then TpuSpill mid-dispatch, abandoning in-flight
        # chunks (the invariant smart_chain.py stages around)
        ex = _build("tpu", [(split_module(), None)], mesh=4).tpu_chain
        assert ex._sharded is not None and ex._fanout
        assert ex.max_stageable_width() == MAX_WIDTH
        assert ex._striped_chain() is not None  # single-device could
