"""Telemetry subsystem: histogram math, span ring, span attribution in
the pipelined stream loop, and counter wiring through the engine.

Covers the ISSUE-2 satellite matrix: bucket boundaries / merge /
percentile interpolation, ring-buffer wraparound, and the fused-path
span capture where batch k's fetch overlaps batch k+1's dispatch.
"""

import numpy as np
import pytest

from fluvio_tpu.models import lookup
from fluvio_tpu.protocol.record import Record
from fluvio_tpu.smartengine import SmartEngine, SmartModuleConfig
from fluvio_tpu.smartengine.tpu.buffer import RecordBuffer
from fluvio_tpu.smartmodule import SmartModuleInput
from fluvio_tpu.telemetry import (
    TELEMETRY,
    BatchSpan,
    LatencyHistogram,
    PipelineTelemetry,
    SpanRing,
)
from fluvio_tpu.telemetry.histogram import BUCKET_BOUNDS, N_BUCKETS
from fluvio_tpu.telemetry.spans import PHASES


@pytest.fixture(autouse=True)
def _fresh_registry():
    """Every test starts from a clean process-global registry (other
    suites run chains too; their batches must not leak into counts)."""
    TELEMETRY.reset()
    prior = TELEMETRY.enabled
    TELEMETRY.enabled = True
    yield
    TELEMETRY.enabled = prior
    TELEMETRY.reset()


def build_chain(backend, specs):
    b = SmartEngine(backend=backend).builder()
    for name, params in specs:
        b.add_smart_module(SmartModuleConfig(params=params or {}), lookup(name))
    return b.initialize()


def make_buf(values):
    records = [Record(value=v) for v in values]
    for i, r in enumerate(records):
        r.offset_delta = i
    return RecordBuffer.from_records(records)


class TestHistogram:
    def test_bucket_boundaries_are_fixed_geometric(self):
        assert len(BUCKET_BOUNDS) == N_BUCKETS - 1
        ratios = [
            BUCKET_BOUNDS[i + 1] / BUCKET_BOUNDS[i]
            for i in range(len(BUCKET_BOUNDS) - 1)
        ]
        assert all(abs(r - 2**0.5) < 1e-9 for r in ratios)
        # ladder spans microseconds to minutes
        assert BUCKET_BOUNDS[0] == pytest.approx(1e-6)
        assert BUCKET_BOUNDS[-1] > 180

    def test_record_lands_in_expected_bucket(self):
        h = LatencyHistogram()
        h.record(0.0)  # below the first bound -> bucket 0
        assert h.counts[0] == 1
        h2 = LatencyHistogram()
        # exactly ON a boundary goes to the NEXT bucket (bounds are
        # upper-inclusive-exclusive via bisect_right)
        h2.record(BUCKET_BOUNDS[3])
        assert h2.counts[4] == 1
        h3 = LatencyHistogram()
        h3.record(BUCKET_BOUNDS[-1] * 10)  # overflow -> +Inf bucket
        assert h3.counts[-1] == 1

    def test_negative_observation_clamps_to_zero(self):
        h = LatencyHistogram()
        h.record(-1.0)
        assert h.counts[0] == 1 and h.min == 0.0

    def test_merge_adds_counts_and_stats(self):
        a, b = LatencyHistogram(), LatencyHistogram()
        for v in (0.001, 0.002, 0.004):
            a.record(v)
        for v in (0.5, 1.0):
            b.record(v)
        a.merge(b)
        assert a.count == 5
        assert a.sum == pytest.approx(1.507)
        assert a.min == pytest.approx(0.001)
        assert a.max == pytest.approx(1.0)
        assert sum(a.counts) == 5

    def test_diff_recovers_delta_observations(self):
        h = LatencyHistogram()
        h.record(0.01)
        snap = h.copy()
        h.record(0.02)
        h.record(0.03)
        d = h.diff(snap)
        assert d.count == 2
        assert d.sum == pytest.approx(0.05)
        assert sum(d.counts) == 2

    def test_percentile_interpolation(self):
        h = LatencyHistogram()
        # 100 observations in one bucket: p0..p100 interpolate linearly
        # across that bucket's [lo, hi)
        for _ in range(100):
            h.record(0.010)
        i = next(j for j, c in enumerate(h.counts) if c)
        lo = BUCKET_BOUNDS[i - 1]
        hi = BUCKET_BOUNDS[i]
        assert lo <= h.percentile(50) <= hi
        assert h.percentile(1) < h.percentile(99)
        # p100 reaches the bucket's upper bound exactly
        assert h.percentile(100) == pytest.approx(hi)

    def test_percentile_across_buckets(self):
        h = LatencyHistogram()
        for _ in range(90):
            h.record(0.001)
        for _ in range(10):
            h.record(1.0)
        assert h.percentile(50) < 0.01
        assert h.percentile(99) > 0.5
        assert h.percentile(0) == 0.0 or h.percentile(0) <= 0.001 * 2

    def test_empty_histogram(self):
        h = LatencyHistogram()
        assert h.percentile(50) == 0.0
        assert h.mean() == 0.0
        d = h.to_dict()
        assert d["count"] == 0

    def test_cumulative_buckets_monotone_with_inf(self):
        h = LatencyHistogram()
        for v in (0.001, 0.1, 10.0, 10_000.0):
            h.record(v)
        buckets = h.cumulative_buckets()
        cums = [c for _, c in buckets]
        assert cums == sorted(cums)
        assert buckets[-1][0] is None  # +Inf always present
        assert buckets[-1][1] == h.count


class TestSpanRing:
    def test_wraparound_keeps_most_recent_in_order(self):
        ring = SpanRing(4)
        spans = []
        for i in range(10):
            s = BatchSpan()
            s.records = i
            ring.push(s)
            spans.append(s)
        assert len(ring) == 4
        assert ring.total == 10
        assert [s.records for s in ring.recent()] == [6, 7, 8, 9]
        assert [s.records for s in ring.recent(limit=2)] == [8, 9]

    def test_under_capacity(self):
        ring = SpanRing(8)
        for i in range(3):
            s = BatchSpan()
            s.records = i
            ring.push(s)
        assert len(ring) == 3
        assert [s.records for s in ring.recent()] == [0, 1, 2]

    def test_zero_capacity_rejected(self):
        with pytest.raises(ValueError):
            SpanRing(0)


class TestSpanAttribution:
    """Fused-path spans through the real executor on the CPU backend."""

    def test_process_buffer_records_full_span(self):
        chain = build_chain(
            "tpu",
            [("regex-filter", {"regex": "fluvio"}), ("json-map", {"field": "name"})],
        )
        assert chain.backend_in_use == "tpu"
        buf = make_buf(
            [b'{"name":"fluvio-%d"}' % i for i in range(64)]
            + [b'{"name":"kafka"}'] * 64
        )
        out = chain.tpu_chain.process_buffer(buf)
        assert out.count == 64
        spans = TELEMETRY.spans.recent()
        assert len(spans) == 1
        span = spans[0]
        assert span.path == "fused"
        # records carries INPUT records (same semantic as the
        # interpreter path, so per-path counters compare workloads)
        assert span.records == 128
        assert span.t_end is not None and span.t_end > span.t0
        d = span.to_dict()
        # the serial pass walks every hot phase
        for phase in ("stage", "dispatch", "device"):
            assert d["phases_ms"].get(phase, 0) > 0, phase
        assert set(d["phases_ms"]) <= set(PHASES)
        # attributed time cannot exceed wall (phases are disjoint clock
        # pairs within one serial batch)
        assert sum(d["phases_ms"].values()) <= d["e2e_ms"] * 1.05
        snap = TELEMETRY.snapshot()
        assert snap["batches"]["fused"]["count"] == 1
        assert snap["batches"]["fused"]["records"] == 128
        assert snap["phases"]["device"]["count"] == 1

    def test_pipelined_stream_overlap_attribution(self):
        """Batch k's fetch overlaps batch k+1's dispatch in
        process_stream; every batch must still get exactly one span and
        the overlap must show up in the span timestamps."""
        chain = build_chain("tpu", [("regex-filter", {"regex": "fluvio"})])
        bufs = [
            make_buf(
                [b'{"name":"fluvio-%d"}' % i for i in range(32)]
                + [b'{"name":"other"}'] * 32
            )
            for _ in range(5)
        ]
        outs = list(chain.tpu_chain.process_stream(iter(bufs)))
        assert len(outs) == 5 and all(o.count == 32 for o in outs)
        spans = TELEMETRY.spans.recent()
        assert len(spans) == 5
        # spans complete in batch order...
        ends = [s.t_end for s in spans]
        assert ends == sorted(ends)
        # ...and the pipeline overlaps: batch k+1's span OPENS (dispatch
        # side) before batch k's span CLOSES (fetch side) — the loop
        # dispatches ahead by construction
        overlaps = [
            spans[k + 1].t0 < spans[k].t_end for k in range(len(spans) - 1)
        ]
        assert all(overlaps)
        # device time was attributed from each batch's own dispatch->sync
        # clock pair, not from the finish call's start
        for s in spans:
            assert s.phase("device") >= 0.0
            assert s.phase("dispatch") > 0.0

    def test_disabled_capture_records_nothing(self):
        TELEMETRY.enabled = False
        chain = build_chain("tpu", [("regex-filter", {"regex": "x"})])
        buf = make_buf([b"x1", b"y2"])
        out = chain.tpu_chain.process_buffer(buf)
        assert out.count == 1
        assert len(TELEMETRY.spans.recent()) == 0
        assert TELEMETRY.snapshot()["batches"]["fused"]["count"] == 0

    def test_interpreter_path_records_batch(self):
        chain = build_chain("python", [("regex-filter", {"regex": "fluvio"})])
        records = [Record(value=b"fluvio"), Record(value=b"kafka")]
        for i, r in enumerate(records):
            r.offset_delta = i
        out = chain.process(SmartModuleInput.from_records(records))
        assert out.error is None
        snap = TELEMETRY.snapshot()
        assert snap["batches"]["interpreter"]["count"] == 1
        assert snap["batches"]["interpreter"]["records"] == 2
        # per-instance interpreter accounting rode along
        interp = snap["counters"]["interp_instance"]
        assert interp["calls"] == 1 and interp["records"] == 2


class TestCounters:
    def test_decline_and_spill_counters(self):
        t = PipelineTelemetry()
        t.add_decline("no-raw-records")
        t.add_decline("no-raw-records")
        t.add_spill("transform-error")
        t.add_heal()
        t.add_stripe_fallback()
        c = t.snapshot()["counters"]
        assert c["declines"] == {"no-raw-records": 2}
        assert c["spills"] == {"transform-error": 1}
        assert c["heals"] == 1 and c["stripe_fallbacks"] == 1

    def test_spill_rerun_records_spill_phase(self):
        """A fused-path spill re-runs on the interpreter and books the
        rerun's wall time under the ``spill`` phase."""
        chain = build_chain("tpu", [("array-map-json", None)])
        assert chain.backend_in_use == "tpu"
        records = [Record(value=b"[1,2]"), Record(value=b"not-an-array")]
        for i, r in enumerate(records):
            r.offset_delta = i
        out = chain.process(SmartModuleInput.from_records(records))
        assert out.error is not None  # exact error came from the rerun
        snap = TELEMETRY.snapshot()
        assert sum(snap["counters"]["spills"].values()) == 1
        assert snap["phases"].get("spill", {}).get("count", 0) == 1
        assert snap["batches"]["interpreter"]["count"] == 1
