"""Metrics export surfaces: Prometheus exposition over the monitoring
socket, JSON/Prometheus/CLI snapshot parity, and the span dump.

ISSUE-2 acceptance: the Prometheus endpoint and the `fluvio-tpu metrics`
CLI must render the SAME snapshot, the exposition must be valid
text-format, and every declared series must be present.
"""

import asyncio
import json
import re

import pytest

from fluvio_tpu.cli.metrics import render_metrics_table
from fluvio_tpu.spu.metrics import SpuMetrics
from fluvio_tpu.spu.monitoring import (
    MonitoringServer,
    read_metrics,
    read_prometheus,
    read_spans,
)
from fluvio_tpu.telemetry import TELEMETRY, render_prometheus


@pytest.fixture(autouse=True)
def _fresh_registry():
    TELEMETRY.reset()
    prior = TELEMETRY.enabled
    TELEMETRY.enabled = True
    yield
    TELEMETRY.enabled = prior
    TELEMETRY.reset()


class _Ctx:
    def __init__(self):
        self.metrics = SpuMetrics()


def _populate():
    """Drive representative traffic into every counter family."""
    span = TELEMETRY.begin_batch(chain="filter+map")
    span.add("stage", 0.002)
    span.add("dispatch", 0.001)
    span.add("device", 0.010)
    span.add("d2h", 0.003)
    TELEMETRY.end_batch(span, records=128)
    ispan = TELEMETRY.begin_batch(path="interpreter")
    TELEMETRY.end_batch(ispan, records=16)
    TELEMETRY.add_heal()
    TELEMETRY.add_stripe_fallback()
    TELEMETRY.add_spill("transform-error")
    TELEMETRY.add_decline("no-raw-records")
    TELEMETRY.add_interp_instance(0.004, 16)
    ctx = _Ctx()
    ctx.metrics.inbound.add(128, 4096)
    ctx.metrics.outbound.add(64, 2048)
    ctx.metrics.smartmodule.add_bytes_in(4096)
    ctx.metrics.smartmodule.add_fastpath()
    ctx.metrics.smartmodule.add_fallback("no-raw-records")
    return ctx


# a sample line is `name value` or `name{labels} value` with a float/int
_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*\})?'
    r" (?:[-+]?[0-9]*\.?[0-9]+(?:[eE][-+]?[0-9]+)?|\+Inf|-Inf|NaN)$"
)

DECLARED_SERIES = [
    "fluvio_tpu_batch_latency_seconds",
    "fluvio_tpu_phase_seconds",
    "fluvio_tpu_chain_e2e_latency_seconds",
    "fluvio_tpu_sharded_inline_compress_shards_total",
    "fluvio_tpu_slo_verdict",
    "fluvio_tpu_batch_records_total",
    "fluvio_tpu_glz_heals_total",
    "fluvio_tpu_stripe_fallbacks_total",
    "fluvio_tpu_spills_total",
    "fluvio_tpu_declines_total",
    "fluvio_tpu_interp_instance_calls_total",
    "fluvio_tpu_interp_instance_seconds_total",
    "fluvio_tpu_interp_instance_records_total",
    "fluvio_tpu_spu_inbound_records_total",
    "fluvio_tpu_spu_inbound_bytes_total",
    "fluvio_tpu_spu_outbound_records_total",
    "fluvio_tpu_spu_outbound_bytes_total",
    "fluvio_tpu_smartmodule_bytes_in_total",
    "fluvio_tpu_smartmodule_fastpath_slices_total",
    "fluvio_tpu_smartmodule_fallback_slices_total",
    "fluvio_tpu_smartmodule_fallback_reasons_total",
]


def _sample_value(text: str, name: str, labels: str = "") -> float:
    target = f"{name}{labels} "
    for line in text.splitlines():
        if line.startswith(target):
            return float(line.split(" ")[-1])
    raise AssertionError(f"no sample {target!r}")


class TestExpositionFormat:
    def test_text_format_validity_and_declared_series(self):
        ctx = _populate()
        text = render_prometheus(spu_metrics=ctx.metrics.to_dict())
        assert text.endswith("\n")
        helped, typed = set(), set()
        for line in text.splitlines():
            if not line:
                continue
            if line.startswith("# HELP "):
                helped.add(line.split(" ")[2])
                continue
            if line.startswith("# TYPE "):
                parts = line.split(" ")
                assert parts[3] in ("counter", "gauge", "histogram")
                typed.add(parts[2])
                continue
            assert _SAMPLE_RE.match(line), f"invalid exposition line: {line!r}"
        for series in DECLARED_SERIES:
            assert series in typed, f"series {series} missing TYPE"
            assert series in helped, f"series {series} missing HELP"
            base = series.replace("_total", "")
            assert any(
                l.startswith(series) or l.startswith(base)
                for l in text.splitlines()
                if not l.startswith("#")
            ), f"series {series} has no samples"

    def test_every_histogram_family_emits_sum_count_with_parity(self):
        """SLO-PR satellite: every latency family must expose ``_sum``
        and ``_count`` (scrapers cannot compute true means from buckets
        alone), and both must agree exactly with the JSON snapshot's
        totals for the same instant."""
        _populate()
        TELEMETRY.add_compile("ragged", "sig", 0.25)
        text = render_prometheus()
        snap = TELEMETRY.snapshot()
        # discover every declared histogram family from the exposition
        families = [
            line.split(" ")[2]
            for line in text.splitlines()
            if line.startswith("# TYPE ") and line.endswith(" histogram")
        ]
        assert set(families) >= {
            "fluvio_tpu_batch_latency_seconds",
            "fluvio_tpu_phase_seconds",
            "fluvio_tpu_chain_e2e_latency_seconds",
            "fluvio_tpu_compile_latency_seconds",
        }
        for family in families:
            sums = [
                l for l in text.splitlines()
                if l.startswith(f"{family}_sum")
            ]
            counts = [
                l for l in text.splitlines()
                if l.startswith(f"{family}_count")
            ]
            assert sums and counts, f"{family} missing _sum/_count"
            assert len(sums) == len(counts)
        # exact parity against the snapshot totals (count is integral,
        # sum within the snapshot's own rounding)
        for path, b in snap["batches"].items():
            assert b["count"] == _sample_value(
                text,
                "fluvio_tpu_batch_latency_seconds_count",
                f'{{path="{path}"}}',
            )
            assert _sample_value(
                text,
                "fluvio_tpu_batch_latency_seconds_sum",
                f'{{path="{path}"}}',
            ) == pytest.approx(b["sum_s"], abs=1e-5)
        for phase, h in snap["phases"].items():
            assert h["count"] == _sample_value(
                text, "fluvio_tpu_phase_seconds_count",
                f'{{phase="{phase}"}}',
            )
            assert _sample_value(
                text, "fluvio_tpu_phase_seconds_sum",
                f'{{phase="{phase}"}}',
            ) == pytest.approx(h["sum_s"], abs=1e-5)
        for chain, h in snap["chains"].items():
            assert h["count"] == _sample_value(
                text, "fluvio_tpu_chain_e2e_latency_seconds_count",
                f'{{chain="{chain}"}}',
            )
            assert _sample_value(
                text, "fluvio_tpu_chain_e2e_latency_seconds_sum",
                f'{{chain="{chain}"}}',
            ) == pytest.approx(h["sum_s"], abs=1e-5)
        comp = snap["compile"]["latency"]
        assert comp["count"] == _sample_value(
            text, "fluvio_tpu_compile_latency_seconds_count"
        )
        assert _sample_value(
            text, "fluvio_tpu_compile_latency_seconds_sum"
        ) == pytest.approx(comp["sum_s"], abs=1e-5)

    def test_histogram_invariants(self):
        ctx = _populate()
        text = render_prometheus(spu_metrics=ctx.metrics.to_dict())
        # +Inf cumulative bucket equals the series count, per label set
        count = _sample_value(
            text, "fluvio_tpu_batch_latency_seconds_count", '{path="fused"}'
        )
        inf = _sample_value(
            text,
            "fluvio_tpu_batch_latency_seconds_bucket",
            '{path="fused",le="+Inf"}',
        )
        assert count == inf == 1
        # cumulative buckets are monotone non-decreasing
        pat = re.compile(
            r'fluvio_tpu_phase_seconds_bucket\{phase="device",le="([^"]+)"\} (\S+)'
        )
        cums = [float(m.group(2)) for m in pat.finditer(text)]
        assert cums and cums == sorted(cums)


class TestSnapshotParity:
    def test_prom_json_and_cli_render_the_same_snapshot(self):
        ctx = _populate()
        data = ctx.metrics.to_dict()
        text = render_prometheus(spu_metrics=data)
        tel = data["telemetry"]
        # counts agree between the JSON snapshot and the exposition
        assert tel["batches"]["fused"]["count"] == _sample_value(
            text, "fluvio_tpu_batch_latency_seconds_count", '{path="fused"}'
        )
        assert tel["batches"]["interpreter"]["records"] == _sample_value(
            text, "fluvio_tpu_batch_records_total", '{path="interpreter"}'
        )
        assert tel["counters"]["heals"] == _sample_value(
            text, "fluvio_tpu_glz_heals_total"
        )
        assert tel["counters"]["spills"]["transform-error"] == _sample_value(
            text, "fluvio_tpu_spills_total", '{reason="transform-error"}'
        )
        assert data["inbound"]["records"] == _sample_value(
            text, "fluvio_tpu_spu_inbound_records_total"
        )
        # the CLI table renders the same snapshot dict: every counter the
        # satellites added must be visible in the human surface
        table = render_metrics_table(data)
        assert "no-raw-records" in table       # fallback_reasons
        assert "glz_heals" in table and "stripe_fallbacks" in table
        assert "spill[transform-error]" in table
        assert "decline[no-raw-records]" in table
        assert "device" in table               # phase table
        assert "fastpath_slices" in table

    def test_cli_table_handles_empty_snapshot(self):
        ctx = _Ctx()
        table = render_metrics_table(ctx.metrics.to_dict())
        assert "smartmodule" in table and "pipeline events" in table


class TestMonitoringSocket:
    def _roundtrip(self, tmp_path, fn):
        async def run():
            ctx = _populate()
            server = MonitoringServer(ctx, str(tmp_path / "m.sock"))
            await server.start()
            try:
                return await fn(server)
            finally:
                await server.stop()

        return asyncio.run(run())

    def test_prom_scrape_over_socket(self, tmp_path):
        text = self._roundtrip(
            tmp_path, lambda s: read_prometheus(s.path)
        )
        assert "fluvio_tpu_batch_latency_seconds_bucket" in text
        assert _sample_value(text, "fluvio_tpu_glz_heals_total") == 1

    def test_json_includes_telemetry_and_matches_prom(self, tmp_path):
        async def both(server):
            return await read_metrics(server.path), await read_prometheus(
                server.path
            )

        data, text = self._roundtrip(tmp_path, both)
        assert data["telemetry"]["counters"]["heals"] == _sample_value(
            text, "fluvio_tpu_glz_heals_total"
        )
        assert (
            data["telemetry"]["batches"]["fused"]["count"]
            == _sample_value(
                text,
                "fluvio_tpu_batch_latency_seconds_count",
                '{path="fused"}',
            )
        )

    def test_span_dump_over_socket(self, tmp_path):
        spans = self._roundtrip(tmp_path, lambda s: read_spans(s.path))
        assert len(spans) == 2
        fused = [s for s in spans if s["path"] == "fused"]
        assert fused and fused[0]["records"] == 128
        assert fused[0]["phases_ms"]["device"] == pytest.approx(10.0)

    def test_legacy_client_without_mode_line_gets_json(self, tmp_path):
        async def legacy(server):
            reader, writer = await asyncio.open_unix_connection(server.path)
            try:
                return json.loads(await reader.read())
            finally:
                writer.close()

        data = self._roundtrip(tmp_path, legacy)
        assert data["inbound"]["records"] == 128
        assert "telemetry" in data


class TestConcurrentScrapeChaos:
    """ISSUE-7 chaos satellite: monitoring-socket ``prom``/``trace``
    scrapes racing live batch dispatch AND trace-sink rotation. Every
    scrape must parse (valid exposition text / valid trace JSON) and
    the span-ring bookkeeping must reconcile exactly — a race that
    tears a counter shows up as a dropped-span undercount."""

    def test_scrapes_race_dispatch_and_rotation(self, tmp_path):
        import threading

        from fluvio_tpu.models import lookup
        from fluvio_tpu.protocol.record import Record
        from fluvio_tpu.smartengine import SmartEngine, SmartModuleConfig
        from fluvio_tpu.smartengine.tpu.buffer import RecordBuffer
        from fluvio_tpu.spu.monitoring import read_trace
        from fluvio_tpu.telemetry.trace import TraceFileSink

        b = SmartEngine(backend="tpu").builder()
        for name, params in (
            ("regex-filter", {"regex": "fluvio"}),
            ("json-map", {"field": "name"}),
        ):
            b.add_smart_module(SmartModuleConfig(params=params), lookup(name))
        chain = b.initialize()
        assert chain.backend_in_use == "tpu"
        records = [
            Record(value=f'{{"name":"fluvio-{i}","n":{i}}}'.encode())
            for i in range(128)
        ]
        for i, r in enumerate(records):
            r.offset_delta = i
        buf = RecordBuffer.from_records(records)
        # warm outside the race so the chaos window is steady-state
        for out in chain.tpu_chain.process_stream(iter([buf] * 2)):
            pass
        TELEMETRY.reset()

        # tiny rotation bound (floors to 4KiB) + per-span flush: the
        # sink rotates constantly while scrapes hold the registry lock
        sink = TraceFileSink(str(tmp_path / "chaos.json"), max_bytes=1)
        sink.FLUSH_INTERVAL_S = 0.0
        sink.BATCH_EVENTS = 1
        TELEMETRY.trace_sink = sink
        stop = threading.Event()
        errors = []
        batches = [0]

        def traffic():
            try:
                while not stop.is_set():
                    for out in chain.tpu_chain.process_stream(iter([buf])):
                        pass
                    batches[0] += 1
            except Exception as e:  # noqa: BLE001 — surfaced to the assert
                errors.append(repr(e))

        async def chaos():
            ctx = _Ctx()
            server = MonitoringServer(ctx, str(tmp_path / "m.sock"))
            await server.start()
            t = threading.Thread(target=traffic)
            t.start()
            try:
                for _ in range(12):
                    text = await read_prometheus(server.path)
                    for line in text.splitlines():
                        if line and not line.startswith("#"):
                            assert _SAMPLE_RE.match(line), line
                    doc = await read_trace(server.path)
                    assert isinstance(doc["traceEvents"], list)
                    # LIVE reconciliation: the snapshot's span triple is
                    # read under one ring-lock acquisition, so it must
                    # balance even while dispatch is mid-push
                    live = TELEMETRY.snapshot()
                    assert live["spans_total"] == (
                        live["spans_retained"] + live["spans_dropped"]
                    )
            finally:
                stop.set()
                t.join()
                await server.stop()

        try:
            asyncio.run(chaos())
        finally:
            TELEMETRY.trace_sink = None
            sink.close()
        assert not errors, errors[:3]
        assert batches[0] > 0
        # no dropped-span undercount: every batch span is accounted for
        # either retained in the ring or counted as dropped
        snap = TELEMETRY.snapshot()
        assert snap["spans_total"] == batches[0]
        assert snap["spans_total"] == (
            snap["spans_retained"] + snap["spans_dropped"]
        )
        # whichever sink generations survived the rotation storm must
        # be valid JSON documents
        for p in (tmp_path / "chaos.json", tmp_path / "chaos.json.1"):
            if p.exists():
                json.loads(p.read_text())
