"""Always-on telemetry overhead gate (ISSUE-2 CI satellite).

Runs the fused headline chain (regex-filter + json-map, bench config
``2_filter_map``) on the hermetic CPU backend with telemetry ON vs OFF
and asserts the throughput delta stays under the gate — so always-on
instrumentation can't silently regress the hot path.

Methodology: alternating measurement passes (on/off interleaved so
machine drift hits both arms equally), best-of-N per arm (min is the
noise-robust estimator for a fixed workload), and one re-measure retry
before failing. The gate is 2% (ISSUE acceptance) with a small absolute
floor so a sub-millisecond workload can't fail on scheduler jitter.
"""

import os
import time

import numpy as np

from fluvio_tpu.models import lookup
from fluvio_tpu.protocol.record import Record
from fluvio_tpu.smartengine import SmartEngine, SmartModuleConfig
from fluvio_tpu.smartengine.tpu.buffer import RecordBuffer
from fluvio_tpu.telemetry import TELEMETRY

# records/sec delta gate; FLUVIO_TELEMETRY_GATE overrides for tuning
GATE = float(os.environ.get("FLUVIO_TELEMETRY_GATE", "0.02"))
N_RECORDS = 4096
BATCHES_PER_PASS = 6
PASSES_PER_ARM = 4


def _headline_chain():
    b = SmartEngine(backend="tpu").builder()
    for name, params in (
        ("regex-filter", {"regex": "fluvio"}),
        ("json-map", {"field": "name"}),
    ):
        b.add_smart_module(SmartModuleConfig(params=params), lookup(name))
    chain = b.initialize()
    assert chain.backend_in_use == "tpu"
    return chain


def _corpus_buf():
    rng = np.random.default_rng(2024)
    names = ["fluvio", "kafka", "pulsar", "fluvio-tpu", "redpanda", "flink"]
    picks = rng.integers(0, len(names), size=N_RECORDS)
    records = [
        Record(value=f'{{"name":"{names[picks[i]]}-{i & 1023}","n":{i}}}'.encode())
        for i in range(N_RECORDS)
    ]
    for i, r in enumerate(records):
        r.offset_delta = i
    return RecordBuffer.from_records(records)


def _one_pass(executor, buf) -> float:
    t0 = time.perf_counter()
    for out in executor.process_stream(iter([buf] * BATCHES_PER_PASS)):
        pass
    return (time.perf_counter() - t0) / BATCHES_PER_PASS


def _measure(executor, buf):
    """Interleaved best-of per arm: [off, on] x PASSES_PER_ARM."""
    prior = TELEMETRY.enabled
    times = {False: [], True: []}
    try:
        for _ in range(PASSES_PER_ARM):
            for enabled in (False, True):
                TELEMETRY.enabled = enabled
                times[enabled].append(_one_pass(executor, buf))
    finally:
        TELEMETRY.enabled = prior
    return min(times[False]), min(times[True])


def test_telemetry_overhead_under_gate():
    chain = _headline_chain()
    executor = chain.tpu_chain
    buf = _corpus_buf()
    # warm: pay the XLA compile + shape-bucket traces outside the window
    for out in executor.process_stream(iter([buf] * 2)):
        pass

    for attempt in range(5):
        off_s, on_s = _measure(executor, buf)
        # absolute floor: a couple of clock pairs per batch is the real
        # instrumentation cost; a 2% gate on a noisy sub-ms pass isn't
        overhead = max(on_s - off_s, 0.0)
        if overhead <= off_s * GATE or overhead < 200e-6:
            break
    else:
        raise AssertionError(
            f"telemetry overhead {overhead*1e6:.0f}us/batch on a "
            f"{off_s*1e3:.2f}ms batch exceeds the {GATE:.0%} gate "
            f"after 5 measurement rounds"
        )
    rps_off = N_RECORDS / off_s
    rps_on = N_RECORDS / on_s
    # records/sec framing of the same gate (ISSUE acceptance criterion)
    assert rps_on >= rps_off * (1 - GATE) or overhead < 200e-6


def test_trace_sink_overhead_under_gate(tmp_path):
    """ISSUE-5 CI satellite: the headline fused chain with telemetry ON
    AND an active FLUVIO_TRACE file sink must stay within the same <2%
    records/sec gate as bare telemetry — the flight recorder appends
    one bounded JSON chunk per batch, never per record."""
    from fluvio_tpu.telemetry import TraceFileSink

    chain = _headline_chain()
    executor = chain.tpu_chain
    buf = _corpus_buf()
    for out in executor.process_stream(iter([buf] * 2)):
        pass

    sink = TraceFileSink(str(tmp_path / "overhead.json"), 256 << 20)
    prior = TELEMETRY.enabled
    # absolute floor: the sink's honest cost is one bounded (~1KB)
    # buffered write per BATCH; on a loaded CI box the write+flush
    # jitter exceeds a 2% window on a ~5ms batch, so the floor is wider
    # than the bare-telemetry gate's — it still fails hard on any
    # per-record regression (4096 records/batch would dwarf it)
    floor_s = 500e-6

    def _measure_with_sink():
        times = {False: [], True: []}
        try:
            for _ in range(PASSES_PER_ARM):
                for enabled in (False, True):
                    TELEMETRY.enabled = enabled
                    TELEMETRY.trace_sink = sink if enabled else None
                    times[enabled].append(_one_pass(executor, buf))
        finally:
            TELEMETRY.enabled = prior
            TELEMETRY.trace_sink = None
        return min(times[False]), min(times[True])

    try:
        for attempt in range(5):
            off_s, on_s = _measure_with_sink()
            overhead = max(on_s - off_s, 0.0)
            if overhead <= off_s * GATE or overhead < floor_s:
                break
        else:
            raise AssertionError(
                f"telemetry+trace-sink overhead {overhead*1e6:.0f}us/batch "
                f"on a {off_s*1e3:.2f}ms batch exceeds the {GATE:.0%} gate "
                f"after 5 measurement rounds"
            )
    finally:
        sink.close()
    rps_off = N_RECORDS / off_s
    rps_on = N_RECORDS / on_s
    assert rps_on >= rps_off * (1 - GATE) or overhead < floor_s


def test_resilience_seam_overhead_under_gate(monkeypatch):
    """ISSUE-3 CI satellite: the fault-injection seams (`maybe_fire`
    calls threaded through stage/h2d/dispatch/device/fetch) must cost
    <1% rps when nothing is armed. Measured by interleaving the real
    unarmed seam against a no-op'd one, same methodology as the
    telemetry gate above (best-of-N, absolute floor, re-measure)."""
    from fluvio_tpu.resilience import faults

    gate = float(os.environ.get("FLUVIO_RESILIENCE_GATE", "0.01"))
    assert not faults.FAULTS.armed, "suite must measure the unarmed path"
    chain = _headline_chain()
    executor = chain.tpu_chain
    buf = _corpus_buf()
    for out in executor.process_stream(iter([buf] * 2)):
        pass

    real_fire = faults.maybe_fire

    def _measure_seams():
        times = {"noop": [], "seams": []}
        for _ in range(PASSES_PER_ARM):
            for arm in ("noop", "seams"):
                monkeypatch.setattr(
                    faults,
                    "maybe_fire",
                    (lambda point: None) if arm == "noop" else real_fire,
                )
                times[arm].append(_one_pass(executor, buf))
        monkeypatch.setattr(faults, "maybe_fire", real_fire)
        return min(times["noop"]), min(times["seams"])

    for attempt in range(5):
        noop_s, seams_s = _measure_seams()
        overhead = max(seams_s - noop_s, 0.0)
        if overhead <= noop_s * gate or overhead < 200e-6:
            break
    else:
        raise AssertionError(
            f"resilience seams cost {overhead*1e6:.0f}us/batch on a "
            f"{noop_s*1e3:.2f}ms batch — exceeds the {gate:.0%} gate "
            f"after 5 measurement rounds"
        )
    rps_noop = N_RECORDS / noop_s
    rps_seams = N_RECORDS / seams_s
    assert rps_seams >= rps_noop * (1 - gate) or overhead < 200e-6


def test_lockwatch_seam_zero_cost_when_disabled(monkeypatch):
    """ISSUE-7 CI satellite: with ``FLUVIO_LOCKWATCH`` unset,
    `make_lock` must hand back a PLAIN ``threading`` primitive — not a
    wrapper, not a subclass — so the watch seam costs exactly nothing
    per acquire/release on every engine lock."""
    import threading

    from fluvio_tpu.analysis import lockwatch
    from fluvio_tpu.analysis.lockwatch import make_lock

    was_armed = lockwatch.enabled()  # process-start state, pre-delenv
    monkeypatch.delenv("FLUVIO_LOCKWATCH", raising=False)
    assert not lockwatch.enabled()
    assert type(make_lock("gate.probe")) is type(threading.Lock())
    assert isinstance(make_lock("gate.probe", rlock=True),
                      type(threading.RLock()))
    if not was_armed:
        # the locks the live engine created at import time are plain too
        # (tier-1 runs unarmed; the armed differential is a subprocess)
        assert type(TELEMETRY._lock) is type(threading.Lock())


def test_glz_chooser_zero_cost_when_disabled(monkeypatch):
    """ISSUE-8 CI satellite: with link compression off (the CPU
    default), the staging-variant chooser must be ZERO work per
    dispatch — the variant resolves once at executor build, and the
    raw staging path never touches the glz module, the compressor, or
    the pallas gate. Tripwires on every glz entry point prove it over
    a full pipelined pass."""
    from fluvio_tpu.smartengine.tpu import glz, pallas_kernels

    monkeypatch.delenv("FLUVIO_LINK_COMPRESS", raising=False)

    def tripwire(*a, **k):
        raise AssertionError("glz seam touched with link compression off")

    chain = _headline_chain()
    executor = chain.tpu_chain
    assert not executor._link_compress
    buf = _corpus_buf()
    for out in executor.process_stream(iter([buf] * 2)):
        pass
    for mod, name in (
        (glz, "compress"), (glz, "compress_link"), (glz, "decode_link_flat"),
        (glz, "decompress_device"), (glz, "byte_plan_device"),
        (pallas_kernels, "glz_pallas_active"),
        (pallas_kernels, "glz_decode_pallas"),
    ):
        monkeypatch.setattr(mod, name, tripwire)
    _one_pass(executor, buf)  # any glz touch raises


def test_result_encode_zero_cost_when_disabled(monkeypatch):
    """ISSUE-12 CI satellite: with the result-ENCODE ladder off (the
    CPU auto default), the down-link seams must be ZERO work per
    dispatch — the variant resolves once at executor build, and the
    fetch never touches the encoder, the token decoder, the pallas
    encode gate, or the desc-stream packers. Tripwires over a full
    pipelined pass prove it."""
    from fluvio_tpu.smartengine.tpu import glz, pallas_kernels
    from fluvio_tpu.smartengine.tpu.executor import TpuChainExecutor

    monkeypatch.delenv("FLUVIO_RESULT_COMPRESS", raising=False)
    chain = _headline_chain()
    executor = chain.tpu_chain
    assert executor._enc_variant == "off"
    buf = _corpus_buf()
    for out in executor.process_stream(iter([buf] * 2)):
        pass

    def tripwire(*a, **k):
        raise AssertionError("result-encode seam touched while off")

    for mod, name in (
        (glz, "encode_result"), (glz, "decode_result_host"),
        (glz, "enc_match_xla"), (glz, "enc_sequences"),
        (pallas_kernels, "glz_enc_pallas_active"),
        (pallas_kernels, "glz_encode_match"),
    ):
        monkeypatch.setattr(mod, name, tripwire)
    monkeypatch.setattr(TpuChainExecutor, "_down_encode", tripwire)
    monkeypatch.setattr(TpuChainExecutor, "_down_try_fetch", tripwire)
    _one_pass(executor, buf)  # any encode-seam touch raises


def test_fetch_overlap_off_zero_cost(monkeypatch):
    """ISSUE-12 CI satellite, overlap arm: with FLUVIO_FETCH_OVERLAP
    off, the stream loop must never touch the fetch worker pool or the
    deferred-finish surface."""
    from fluvio_tpu.smartengine.tpu import executor as ex_mod

    monkeypatch.setenv("FLUVIO_FETCH_OVERLAP", "off")

    def tripwire(*a, **k):
        raise AssertionError("fetch-overlap seam touched while off")

    monkeypatch.setattr(ex_mod, "_fetch_mat_pool", tripwire)
    monkeypatch.setattr(
        ex_mod.TpuChainExecutor, "finish_buffer_deferred", tripwire
    )
    chain = _headline_chain()
    buf = _corpus_buf()
    for out in chain.tpu_chain.process_stream(iter([buf] * 2)):
        pass


def test_slo_sampler_overhead_under_gate():
    """SLO-PR CI satellite: the time-series sampler + SLO evaluator,
    armed and evaluating once per pass (a far hotter cadence than any
    real scraper), must stay inside the same <2% rps gate. The layer is
    pull-based — per batch it adds exactly one chain-histogram record —
    so the honest cost is the evaluation itself, amortized over the
    pass."""
    from fluvio_tpu.telemetry import SloEngine, TimeSeries

    chain = _headline_chain()
    executor = chain.tpu_chain
    buf = _corpus_buf()
    for out in executor.process_stream(iter([buf] * 2)):
        pass

    # tiny window so every evaluation really ticks + diffs the ring
    eng = SloEngine(timeseries=TimeSeries(window_s=1e-3, capacity=8))
    eng.evaluate()

    def _measure_slo():
        times = {"bare": [], "armed": []}
        for _ in range(PASSES_PER_ARM):
            for arm in ("bare", "armed"):
                t0 = time.perf_counter()
                for out in executor.process_stream(
                    iter([buf] * BATCHES_PER_PASS)
                ):
                    pass
                if arm == "armed":
                    doc = eng.evaluate()
                    assert doc["enabled"] is True
                times[arm].append(
                    (time.perf_counter() - t0) / BATCHES_PER_PASS
                )
        return min(times["bare"]), min(times["armed"])

    for attempt in range(5):
        bare_s, armed_s = _measure_slo()
        overhead = max(armed_s - bare_s, 0.0)
        if overhead <= bare_s * GATE or overhead < 500e-6:
            break
    else:
        raise AssertionError(
            f"slo sampler+evaluator cost {overhead*1e6:.0f}us/batch on a "
            f"{bare_s*1e3:.2f}ms batch — exceeds the {GATE:.0%} gate "
            f"after 5 measurement rounds"
        )
    rps_bare = N_RECORDS / bare_s
    rps_armed = N_RECORDS / armed_s
    assert rps_armed >= rps_bare * (1 - GATE) or overhead < 500e-6


def test_slo_seams_zero_cost_when_telemetry_off(monkeypatch):
    """SLO-PR CI satellite, the strict half: with FLUVIO_TELEMETRY=0
    the whole windowed/SLO layer must be ZERO work — tripwires on the
    registry sampler and the window ring prove neither is touched, and
    the evaluator returns a disabled verdict without evaluating."""
    from fluvio_tpu.telemetry import SloEngine, TimeSeries
    from fluvio_tpu.telemetry import timeseries as ts_mod

    TELEMETRY.reset()
    prior = TELEMETRY.enabled
    TELEMETRY.enabled = False
    try:

        def tripwire(*a, **k):
            raise AssertionError("slo seam touched with telemetry off")

        monkeypatch.setattr(TELEMETRY, "timeseries_sample", tripwire)
        monkeypatch.setattr(ts_mod, "_Cum", tripwire)
        ts = TimeSeries(window_s=1e-3, capacity=4)
        eng = SloEngine(timeseries=ts)
        assert ts.maybe_tick() == 0
        ts.force_tick()
        doc = eng.evaluate()
        assert doc == {"enabled": False, "verdict": "disabled", "chains": {}}
        # the hot-path seam: a disabled begin_batch hands back None, so
        # the per-chain histogram family records nothing
        chain = _headline_chain()
        buf = _corpus_buf()
        for out in chain.tpu_chain.process_stream(iter([buf] * 2)):
            pass
        assert TELEMETRY.chain_hist_copies() == {}
    finally:
        TELEMETRY.enabled = prior
        TELEMETRY.reset()


def test_admission_armed_overhead_under_gate():
    """ISSUE-11 CI satellite: the admission front door — one
    controller decision per slice against a live health engine — must
    stay inside the same <2% rps gate. The decision is a cached-verdict
    read plus a token-bucket charge; the SLO evaluation refreshes at
    most once per FLUVIO_ADMISSION_REFRESH_S, never per slice."""
    from fluvio_tpu.admission import AdmissionController
    from fluvio_tpu.telemetry import SloEngine, TimeSeries

    chain = _headline_chain()
    executor = chain.tpu_chain
    buf = _corpus_buf()
    for out in executor.process_stream(iter([buf] * 2)):
        pass

    ctl = AdmissionController(
        slo_engine=SloEngine(timeseries=TimeSeries(window_s=1.0, capacity=8)),
        refresh_s=1.0,
        tokens=1e9,
        refill=1e9,
    )
    ctl.admit(executor._chain_sig)  # resolve the first evaluation

    def _measure_admission():
        times = {"bare": [], "armed": []}
        for _ in range(PASSES_PER_ARM):
            for arm in ("bare", "armed"):
                t0 = time.perf_counter()
                for i in range(BATCHES_PER_PASS):
                    if arm == "armed":
                        d = ctl.admit(executor._chain_sig)
                        assert d.admitted
                    executor.process_buffer(buf)
                times[arm].append(
                    (time.perf_counter() - t0) / BATCHES_PER_PASS
                )
        return min(times["bare"]), min(times["armed"])

    for attempt in range(5):
        bare_s, armed_s = _measure_admission()
        overhead = max(armed_s - bare_s, 0.0)
        if overhead <= bare_s * GATE or overhead < 500e-6:
            break
    else:
        raise AssertionError(
            f"admission decision cost {overhead*1e6:.0f}us/batch on a "
            f"{bare_s*1e3:.2f}ms batch — exceeds the {GATE:.0%} gate "
            f"after 5 measurement rounds"
        )
    rps_bare = N_RECORDS / bare_s
    rps_armed = N_RECORDS / armed_s
    assert rps_armed >= rps_bare * (1 - GATE) or overhead < 500e-6


def test_admission_seams_zero_cost_when_disabled(monkeypatch):
    """ISSUE-11 CI satellite, the strict half: with FLUVIO_ADMISSION
    unset the broker seam resolves to None ONCE and the whole admission
    layer is untouchable — tripwires on the controller, queue, and
    batcher entry points prove no decision, no enqueue, no gauge, and
    no counter moves through a full slice-path check."""
    from fluvio_tpu import admission
    from fluvio_tpu.admission import controller as ctl_mod
    from fluvio_tpu.admission import fairness as fair_mod
    from fluvio_tpu.admission import batcher as batch_mod
    from fluvio_tpu.spu import smart_chain

    monkeypatch.delenv("FLUVIO_ADMISSION", raising=False)
    admission.reset_gate()

    def tripwire(*a, **k):
        raise AssertionError("admission seam touched while disabled")

    monkeypatch.setattr(
        ctl_mod.AdmissionController, "admit", tripwire
    )
    monkeypatch.setattr(fair_mod.FairQueue, "push", tripwire)
    monkeypatch.setattr(batch_mod.ShapeBucketBatcher, "add", tripwire)

    TELEMETRY.reset()
    chain = _headline_chain()
    buf = _corpus_buf()
    # the broker front-door seam: must resolve None and touch nothing
    assert smart_chain.admission_check(chain) is None
    for out in chain.tpu_chain.process_stream(iter([buf] * 2)):
        pass
    snap = TELEMETRY.snapshot()
    assert snap["counters"]["admission"] == {}
    assert "admission_queue_depth" not in snap["gauges"]
    assert "warmed_buckets" not in snap["gauges"]
    TELEMETRY.reset()


def test_flow_tracing_armed_overhead_under_gate():
    """ISSUE-15 CI satellite: per-slice flow tracing armed — one
    begin_flow/end_flow pair per slice around the REAL dispatch path —
    must stay inside the same <2% rps gate. A flow is one object plus a
    handful of clock reads per SLICE, never per record or chunk."""
    chain = _headline_chain()
    executor = chain.tpu_chain
    buf = _corpus_buf()
    for out in executor.process_stream(iter([buf] * 2)):
        pass
    assert TELEMETRY.flow_trace, "FLUVIO_FLOW_TRACE default must arm"
    sig = executor._chain_sig

    def _measure_flows():
        times = {"bare": [], "armed": []}
        for _ in range(PASSES_PER_ARM):
            for arm in ("bare", "armed"):
                t0 = time.perf_counter()
                for _i in range(BATCHES_PER_PASS):
                    if arm == "armed":
                        f = TELEMETRY.begin_flow(sig)
                        f.mark_dispatch()
                        executor.process_buffer(buf)
                        TELEMETRY.end_flow(f, records=N_RECORDS)
                    else:
                        executor.process_buffer(buf)
                times[arm].append(
                    (time.perf_counter() - t0) / BATCHES_PER_PASS
                )
        return min(times["bare"]), min(times["armed"])

    for attempt in range(5):
        bare_s, armed_s = _measure_flows()
        overhead = max(armed_s - bare_s, 0.0)
        if overhead <= bare_s * GATE or overhead < 500e-6:
            break
    else:
        raise AssertionError(
            f"flow tracing cost {overhead*1e6:.0f}us/slice on a "
            f"{bare_s*1e3:.2f}ms batch — exceeds the {GATE:.0%} gate "
            f"after 5 measurement rounds"
        )
    rps_bare = N_RECORDS / bare_s
    rps_armed = N_RECORDS / armed_s
    assert rps_armed >= rps_bare * (1 - GATE) or overhead < 500e-6


def test_flow_lag_seams_zero_cost_when_telemetry_off(monkeypatch):
    """ISSUE-15 CI satellite, the strict half: with FLUVIO_TELEMETRY=0
    every new seam — slice ring, flow emit, slice histograms, lag
    sampler/registration — is ZERO work. Tripwires prove none is
    touched through a full pipelined pass plus direct seam calls."""
    from fluvio_tpu.telemetry import flow as flow_module
    from fluvio_tpu.telemetry import lag as lag_module

    lag_module.reset_engine()
    TELEMETRY.reset()
    prior = TELEMETRY.enabled
    TELEMETRY.enabled = False
    try:

        def tripwire(*a, **k):
            raise AssertionError("flow/lag seam touched with telemetry off")

        monkeypatch.setattr(flow_module.SliceFlow, "__init__", tripwire)
        monkeypatch.setattr(TELEMETRY.flows, "push", tripwire)
        monkeypatch.setattr(lag_module.LagEngine, "track", tripwire)
        monkeypatch.setattr(lag_module.LagEngine, "sample", tripwire)

        assert TELEMETRY.begin_flow("c") is None
        TELEMETRY.end_flow(None, records=4)
        TELEMETRY.add_slice_phase("hold", 1.0)
        TELEMETRY.add_record_age("c", 1.0)
        TELEMETRY.set_consumer_lag("c", 5)
        TELEMETRY.add_served("c", 5)
        lag_module.track_stream("c", object())
        lag_module.note_commit("c", 1)
        lag_module.note_serve("c", 1, 1.0)
        TELEMETRY.refresh_lag()
        assert TELEMETRY.lag_sampler is None

        chain = _headline_chain()
        buf = _corpus_buf()
        for out in chain.tpu_chain.process_stream(iter([buf] * 2)):
            pass
        snap = TELEMETRY.snapshot()
        assert snap["flows_total"] == 0
        assert snap["slices"] == {}
        assert snap["lag"] == {
            "consumer_lag": {}, "served_records": {}, "record_age": {},
        }
    finally:
        TELEMETRY.enabled = prior
        TELEMETRY.reset()
        lag_module.reset_engine()


def test_soak_accounting_armed_overhead_under_gate():
    """ISSUE-17 CI satellite: the per-tenant accounting plane armed —
    one tenant-attributed admission decision, tenant-labeled flow, and
    served/age booking per slice around the REAL dispatch path — must
    stay inside the same <2% rps gate. Tenant accounting is a couple
    of capped-dict bumps per SLICE, never per record."""
    from fluvio_tpu.admission import AdmissionController
    from fluvio_tpu.telemetry import SloEngine, TimeSeries

    chain = _headline_chain()
    executor = chain.tpu_chain
    buf = _corpus_buf()
    for out in executor.process_stream(iter([buf] * 2)):
        pass

    ctl = AdmissionController(
        slo_engine=SloEngine(timeseries=TimeSeries(window_s=1.0, capacity=8)),
        refresh_s=1.0,
        tokens=1e9,
        refill=1e9,
    )
    sig = executor._chain_sig
    ctl.admit(sig, tenant="acme")  # resolve the first evaluation

    def _measure_soak():
        times = {"bare": [], "armed": []}
        for _ in range(PASSES_PER_ARM):
            for arm in ("bare", "armed"):
                t0 = time.perf_counter()
                for _i in range(BATCHES_PER_PASS):
                    if arm == "armed":
                        d = ctl.admit(sig, tenant="acme")
                        assert d.admitted
                        f = TELEMETRY.begin_flow(sig, tenant="acme")
                        f.mark_dispatch()
                        executor.process_buffer(buf)
                        TELEMETRY.add_tenant_served("acme", N_RECORDS)
                        TELEMETRY.add_tenant_age("acme", 0.001)
                        TELEMETRY.end_flow(f, records=N_RECORDS)
                    else:
                        executor.process_buffer(buf)
                times[arm].append(
                    (time.perf_counter() - t0) / BATCHES_PER_PASS
                )
        return min(times["bare"]), min(times["armed"])

    for attempt in range(5):
        bare_s, armed_s = _measure_soak()
        overhead = max(armed_s - bare_s, 0.0)
        if overhead <= bare_s * GATE or overhead < 500e-6:
            break
    else:
        raise AssertionError(
            f"tenant accounting cost {overhead*1e6:.0f}us/slice on a "
            f"{bare_s*1e3:.2f}ms batch — exceeds the {GATE:.0%} gate "
            f"after 5 measurement rounds"
        )
    rps_bare = N_RECORDS / bare_s
    rps_armed = N_RECORDS / armed_s
    assert rps_armed >= rps_bare * (1 - GATE) or overhead < 500e-6


def test_tenant_seams_zero_cost_when_telemetry_off(monkeypatch):
    """ISSUE-17 CI satellite, the strict half: with FLUVIO_TELEMETRY=0
    every tenant seam — served/shed/held counters, age histograms, the
    cardinality-cap fold, the tenant-labeled flow — is ZERO work.
    Every ``add_tenant_*`` routes through the cap resolver once it
    does real work, so one tripwire there covers the whole family."""
    TELEMETRY.reset()
    prior = TELEMETRY.enabled
    TELEMETRY.enabled = False
    try:

        def tripwire(*a, **k):
            raise AssertionError("tenant seam touched with telemetry off")

        monkeypatch.setattr(TELEMETRY, "_tenant_key", tripwire)
        TELEMETRY.add_tenant_served("acme", 64)
        TELEMETRY.add_tenant_shed("acme")
        TELEMETRY.add_tenant_held("acme")
        TELEMETRY.add_tenant_age("acme", 0.5)
        assert TELEMETRY.begin_flow("c", tenant="acme") is None
        served, shed, held, ages = TELEMETRY.tenant_families()
        assert served == {} and shed == {} and held == {} and ages == {}
        snap = TELEMETRY.snapshot()
        assert snap["tenants"] == {
            "served": {}, "shed": {}, "held": {}, "age": {},
        }
    finally:
        TELEMETRY.enabled = prior
        TELEMETRY.reset()


def test_telemetry_disabled_skips_span_capture_entirely():
    """The off switch must mean OFF: no spans, no histogram writes."""
    chain = _headline_chain()
    buf = _corpus_buf()
    TELEMETRY.reset()
    prior = TELEMETRY.enabled
    TELEMETRY.enabled = False
    try:
        for out in chain.tpu_chain.process_stream(iter([buf] * 2)):
            pass
        snap = TELEMETRY.snapshot()
        assert snap["spans_total"] == 0
        assert snap["batches"]["fused"]["count"] == 0
        assert not snap["phases"]
        assert not snap["chains"]  # per-chain family is span-gated too
        # ISSUE-5: the compile/gauge/event seams are zero-cost too —
        # nothing may record while capture is off
        assert snap["compile"]["by_kind"] == {}
        assert snap["compile"]["jit_cache_hits"] == 0
        assert snap["gauges"] == {}
        assert snap["events_total"] == 0
    finally:
        TELEMETRY.enabled = prior
        TELEMETRY.reset()


def test_partition_armed_overhead_under_gate():
    """ISSUE-13 CI satellite: the partition runtime's per-batch work —
    a state lookup, the carry-slot swap, the identity labels, and the
    group-device scope — must stay inside the same <2% rps gate
    against the bare executor."""
    from fluvio_tpu.partition.placement import (
        parse_placement_rules,
        plan_placement,
    )
    from fluvio_tpu.partition.runtime import PartitionRuntime

    chain = _headline_chain()
    executor = chain.tpu_chain
    buf = _corpus_buf()
    for out in executor.process_stream(iter([buf] * 2)):
        pass
    runtime = PartitionRuntime(
        executor,
        plan_placement(parse_placement_rules(".*=spread"), [], 2),
        chain=chain,
    )
    runtime.process("t", 0, buf)  # resolve the partition state once

    def _measure_partition():
        times = {"bare": [], "armed": []}
        for _ in range(PASSES_PER_ARM):
            for arm in ("bare", "armed"):
                t0 = time.perf_counter()
                for _i in range(BATCHES_PER_PASS):
                    if arm == "armed":
                        runtime.process("t", 0, buf)
                    else:
                        executor.process_buffer(buf)
                times[arm].append(
                    (time.perf_counter() - t0) / BATCHES_PER_PASS
                )
        return min(times["bare"]), min(times["armed"])

    for attempt in range(5):
        bare_s, armed_s = _measure_partition()
        overhead = max(armed_s - bare_s, 0.0)
        if overhead <= bare_s * GATE or overhead < 500e-6:
            break
    else:
        raise AssertionError(
            f"partition runtime cost {overhead*1e6:.0f}us/batch on a "
            f"{bare_s*1e3:.2f}ms batch — exceeds the {GATE:.0%} gate "
            f"after 5 measurement rounds"
        )


def test_partition_seam_zero_cost_when_disabled(monkeypatch):
    """ISSUE-13 CI satellite, the strict half: with FLUVIO_PARTITIONS
    unset the broker seam resolves to None ONCE and the partition layer
    is untouchable — tripwires on the gate, the scope, and the runtime
    prove no plan, no placement, no identity label, and no tagged
    counter moves through a full pipelined pass."""
    from fluvio_tpu import partition
    from fluvio_tpu.partition import runtime as rt_mod
    from fluvio_tpu.spu import smart_chain

    monkeypatch.delenv("FLUVIO_PARTITIONS", raising=False)
    partition.reset_gate()

    def tripwire(*a, **k):
        raise AssertionError("partition seam touched while disabled")

    monkeypatch.setattr(rt_mod.BrokerPartitionGate, "__init__", tripwire)
    monkeypatch.setattr(rt_mod.BrokerPartitionGate, "scope", tripwire)
    monkeypatch.setattr(rt_mod.PartitionRuntime, "dispatch", tripwire)
    monkeypatch.setattr(rt_mod.PartitionRuntime, "finish", tripwire)

    TELEMETRY.reset()
    chain = _headline_chain()
    buf = _corpus_buf()
    assert smart_chain._partition_gate() is None
    for out in chain.tpu_chain.process_stream(iter([buf] * 2)):
        pass
    # the executor's identity stayed unpartitioned: no tagged counters
    assert chain.tpu_chain.span_chain is None
    assert chain.tpu_chain.partition_tag is None
    snap = TELEMETRY.snapshot()
    assert not [
        k for k in snap["counters"]["link_variants"] if "@" in k
    ]
    assert not [k for k in snap["counters"]["declines"] if "@" in k]
    TELEMETRY.reset()


def test_windowed_armed_overhead_under_gate():
    """ISSUE-19 CI satellite: the windowed engine's telemetry — batch
    spans on the "windowed" path, the window counter family, the
    downlink split, and the state-bytes gauge — must stay inside the
    same <2% rps gate measured ON vs OFF over the REAL device fold."""
    from fluvio_tpu.windows import WindowSpec, WindowedRuntime

    spec = WindowSpec(window_ms=1000, op="add", lateness_ms=0,
                      capacity=512, emit_capacity=256, delta_only=True)
    rt = WindowedRuntime(spec)
    contribs = np.arange(N_RECORDS, dtype=np.int64)
    keys = np.zeros(N_RECORDS, dtype=np.int64)
    ts = (np.arange(N_RECORDS, dtype=np.int64) * 4) % 8000
    rt.ingest_arrays(contribs, keys, ts)  # pay the compile outside

    def _one_windowed_pass() -> float:
        t0 = time.perf_counter()
        for _ in range(BATCHES_PER_PASS):
            rt.ingest_arrays(contribs, keys, ts)
        return (time.perf_counter() - t0) / BATCHES_PER_PASS

    def _measure_windowed():
        prior = TELEMETRY.enabled
        times = {False: [], True: []}
        try:
            for _ in range(PASSES_PER_ARM):
                for enabled in (False, True):
                    TELEMETRY.enabled = enabled
                    times[enabled].append(_one_windowed_pass())
        finally:
            TELEMETRY.enabled = prior
        return min(times[False]), min(times[True])

    for attempt in range(5):
        off_s, on_s = _measure_windowed()
        overhead = max(on_s - off_s, 0.0)
        if overhead <= off_s * GATE or overhead < 500e-6:
            break
    else:
        raise AssertionError(
            f"windowed telemetry overhead {overhead*1e6:.0f}us/batch on "
            f"a {off_s*1e3:.2f}ms batch exceeds the {GATE:.0%} gate "
            f"after 5 measurement rounds"
        )
    rps_off = N_RECORDS / off_s
    rps_on = N_RECORDS / on_s
    assert rps_on >= rps_off * (1 - GATE) or overhead < 500e-6


def test_window_seams_zero_cost_when_telemetry_off():
    """ISSUE-19 CI satellite, the strict half: with FLUVIO_TELEMETRY=0
    a windowed batch books NO span, no phase split, and no gauge — the
    engine's span-gated timers all skip. The window counter family
    (closed/deltas/downlink bytes) stays always-on by the same rule as
    admission: those counts are exactness evidence the bench pins diff
    around runs, not observability sugar."""
    from fluvio_tpu.windows import WindowSpec, WindowedRuntime

    spec = WindowSpec(window_ms=100, op="add", lateness_ms=0,
                      capacity=64, emit_capacity=32, delta_only=True)
    TELEMETRY.reset()
    prior = TELEMETRY.enabled
    TELEMETRY.enabled = False
    try:
        rt = WindowedRuntime(spec)
        contribs = np.arange(64, dtype=np.int64)
        keys = np.zeros(64, dtype=np.int64)
        ts = np.arange(64, dtype=np.int64) * 5
        delta = rt.ingest_arrays(contribs, keys, ts)
        snap = TELEMETRY.snapshot()
        assert snap["spans_total"] == 0
        assert not snap["phases"]
        assert "window_state_bytes" not in snap["gauges"]
        # the always-on exactness counters DID move
        closed, kinds, delta_bytes, full_bytes = TELEMETRY.window_counts()
        assert delta_bytes == delta.delta_bytes
        assert full_bytes == delta.full_bytes
        assert kinds.get("upsert", 0) + kinds.get("close", 0) >= 1
    finally:
        TELEMETRY.enabled = prior
        TELEMETRY.reset()


def test_memory_ledger_armed_overhead_under_gate():
    """ISSUE-20 CI satellite: the device-memory ledger armed — one
    acquire/release pair per batch on top of the seams the executor
    already books — must stay inside the same <2% rps gate. A ledger
    move is one dict write under one short lock plus four gauge sets,
    per BATCH, never per record."""
    from fluvio_tpu.telemetry import memory as memory_mod

    memory_mod.reset_engine()
    chain = _headline_chain()
    executor = chain.tpu_chain
    buf = _corpus_buf()
    for out in executor.process_stream(iter([buf] * 2)):
        pass
    ledger = memory_mod.engine()

    def _measure_ledger():
        times = {"bare": [], "armed": []}
        for _ in range(PASSES_PER_ARM):
            for arm in ("bare", "armed"):
                t0 = time.perf_counter()
                for i in range(BATCHES_PER_PASS):
                    if arm == "armed":
                        ledger.acquire("compile_cache", ("gate", i), 4096)
                        executor.process_buffer(buf)
                        ledger.release(("gate", i))
                    else:
                        executor.process_buffer(buf)
                times[arm].append(
                    (time.perf_counter() - t0) / BATCHES_PER_PASS
                )
        return min(times["bare"]), min(times["armed"])

    try:
        for attempt in range(5):
            bare_s, armed_s = _measure_ledger()
            overhead = max(armed_s - bare_s, 0.0)
            if overhead <= bare_s * GATE or overhead < 500e-6:
                break
        else:
            raise AssertionError(
                f"ledger booking cost {overhead*1e6:.0f}us/batch on a "
                f"{bare_s*1e3:.2f}ms batch — exceeds the {GATE:.0%} gate "
                f"after 5 measurement rounds"
            )
        rps_bare = N_RECORDS / bare_s
        rps_armed = N_RECORDS / armed_s
        assert rps_armed >= rps_bare * (1 - GATE) or overhead < 500e-6
    finally:
        memory_mod.reset_engine()
        TELEMETRY.reset()


def test_memory_seams_zero_cost_when_telemetry_off(monkeypatch):
    """ISSUE-20 CI satellite, the strict half: with FLUVIO_TELEMETRY=0
    the ledger seams are ONE enabled-check — tripwires on the ledger
    entry points prove no acquire, no release, no sampler install, and
    no gauge moves through a full pipelined pass plus direct seam
    calls. (The ``window_bank`` owner is the documented exception: the
    windowed engine books state bytes always-on as exactness evidence —
    this pass rides the NON-windowed executor path.)"""
    from fluvio_tpu.telemetry import memory as memory_mod

    memory_mod.reset_engine()
    TELEMETRY.reset()
    prior = TELEMETRY.enabled
    TELEMETRY.enabled = False
    try:

        def tripwire(*a, **k):
            raise AssertionError("memory seam touched with telemetry off")

        monkeypatch.setattr(memory_mod.MemoryLedger, "acquire", tripwire)
        monkeypatch.setattr(memory_mod.MemoryLedger, "release", tripwire)
        monkeypatch.setattr(memory_mod.MemoryLedger, "sample", tripwire)

        # direct seam calls: all gated to a single enabled check
        TELEMETRY.mem_acquire("staged_batch", ("b", 1), 4096)
        TELEMETRY.mem_release(("b", 1))
        TELEMETRY.refresh_memory()
        assert TELEMETRY.mem_sampler is None

        chain = _headline_chain()
        buf = _corpus_buf()
        for out in chain.tpu_chain.process_stream(iter([buf] * 2)):
            pass
        # nothing minted a ledger, and the snapshot's memory section
        # reads honest zeros
        assert memory_mod.peek() is None
        snap = TELEMETRY.snapshot()
        assert snap["memory"] == {
            "owners": {}, "total_bytes": 0, "peak_bytes": 0, "leaks": {},
        }
        assert "device_memory_bytes" not in snap["gauges"]
        assert "hbm_staged_bytes" not in snap["gauges"]
    finally:
        TELEMETRY.enabled = prior
        TELEMETRY.reset()
        memory_mod.reset_engine()
