"""TLS transport + x509 identity (parity: fluvio/src/config/tls.rs,
fluvio-auth/src/x509/).

Loopback: a throwaway CA signs a server cert (CN=localhost) and a client
cert (CN=alice, O=admins); the SPU terminates TLS on its public endpoint
and the client connects with a verified TlsPolicy. Covers produce/consume
through TLS end-to-end, anonymous mode, rejection of plaintext clients,
and identity extraction from the client certificate.
"""

from __future__ import annotations

import asyncio
import datetime

import pytest

cryptography = pytest.importorskip("cryptography")

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import rsa
from cryptography.x509.oid import NameOID

from fluvio_tpu.auth.identity import Identity
from fluvio_tpu.client import ConsumerConfig, Fluvio, Offset, TlsPolicy
from fluvio_tpu.spu import SpuConfig, SpuServer
from fluvio_tpu.storage.config import ReplicaConfig
from fluvio_tpu.transport.tls import ServerTlsConfig


def _key():
    return rsa.generate_private_key(public_exponent=65537, key_size=2048)


def _name(cn: str, org: str | None = None):
    attrs = [x509.NameAttribute(NameOID.COMMON_NAME, cn)]
    if org:
        attrs.append(x509.NameAttribute(NameOID.ORGANIZATION_NAME, org))
    return x509.Name(attrs)


def _cert(subject, issuer, subject_key, issuer_key, ca=False, san=None):
    now = datetime.datetime.now(datetime.timezone.utc)
    b = (
        x509.CertificateBuilder()
        .subject_name(subject)
        .issuer_name(issuer)
        .public_key(subject_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=1))
        .add_extension(x509.BasicConstraints(ca=ca, path_length=None), critical=True)
    )
    if san:
        b = b.add_extension(
            x509.SubjectAlternativeName([x509.DNSName(san)]), critical=False
        )
    return b.sign(issuer_key, hashes.SHA256())


def _write(tmp, name, obj, private=False):
    p = tmp / name
    if private:
        p.write_bytes(
            obj.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.TraditionalOpenSSL,
                serialization.NoEncryption(),
            )
        )
    else:
        p.write_bytes(obj.public_bytes(serialization.Encoding.PEM))
    return str(p)


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("tls")
    ca_key = _key()
    ca_cert = _cert(_name("test-ca"), _name("test-ca"), ca_key, ca_key, ca=True)
    srv_key = _key()
    srv_cert = _cert(
        _name("localhost"), _name("test-ca"), srv_key, ca_key, san="localhost"
    )
    cli_key = _key()
    cli_cert = _cert(
        _name("alice", "admins"), _name("test-ca"), cli_key, ca_key
    )
    return {
        "ca": _write(tmp, "ca.crt", ca_cert),
        "server_cert": _write(tmp, "server.crt", srv_cert),
        "server_key": _write(tmp, "server.key", srv_key, private=True),
        "client_cert": _write(tmp, "client.crt", cli_cert),
        "client_key": _write(tmp, "client.key", cli_key, private=True),
    }


def _tls_spu(tmp_path, certs, require_client_cert=False):
    config = SpuConfig(
        id=6001,
        public_addr="127.0.0.1:0",
        log_base_dir=str(tmp_path),
        replication=ReplicaConfig(base_dir=str(tmp_path)),
        tls=ServerTlsConfig(
            enabled=True,
            server_cert=certs["server_cert"],
            server_key=certs["server_key"],
            ca_cert=certs["ca"],
            require_client_cert=require_client_cert,
        ),
    )
    return SpuServer(config)


def _addr(server):
    # bind address is 127.0.0.1; dial by the cert's DNS name
    return "localhost:" + server.public_addr.rsplit(":", 1)[1]


class TestTlsTransport:
    def test_verified_roundtrip(self, tmp_path, certs):
        async def run():
            server = _tls_spu(tmp_path, certs)
            await server.start()
            server.ctx.create_replica("topic", 0)
            policy = TlsPolicy(mode="verified", ca_cert=certs["ca"], domain="localhost")
            client = await Fluvio.connect(_addr(server), tls=policy)
            producer = await client.topic_producer("topic")
            futs = [await producer.send(None, f"tls-{i}".encode()) for i in range(20)]
            await producer.flush()
            for f in futs:
                await f.wait()
            consumer = await client.partition_consumer("topic", 0)
            got = []
            async for r in consumer.stream(
                Offset.beginning(), ConsumerConfig(disable_continuous=True)
            ):
                got.append(r.value)
            assert got == [f"tls-{i}".encode() for i in range(20)]
            await client.close()
            await server.stop()

        asyncio.run(run())

    def test_anonymous_mode(self, tmp_path, certs):
        async def run():
            server = _tls_spu(tmp_path, certs)
            await server.start()
            server.ctx.create_replica("topic", 0)
            client = await Fluvio.connect(
                _addr(server), tls=TlsPolicy(mode="anonymous")
            )
            producer = await client.topic_producer("topic")
            fut = await producer.send(None, b"anon")
            await producer.flush()
            await fut.wait()
            await client.close()
            await server.stop()

        asyncio.run(run())

    def test_plaintext_client_rejected(self, tmp_path, certs):
        async def run():
            server = _tls_spu(tmp_path, certs)
            await server.start()
            with pytest.raises((ConnectionError, OSError, asyncio.TimeoutError)):
                await asyncio.wait_for(
                    Fluvio.connect(_addr(server)), timeout=3
                )
            await server.stop()

        asyncio.run(run())

    def test_client_cert_identity(self, tmp_path, certs):
        """Server with client-cert verification attests x509 identity."""
        seen = {}

        async def run():
            server = _tls_spu(tmp_path, certs, require_client_cert=True)
            # intercept the service to capture the socket's identity
            service = server.public_server.service
            orig = service.respond

            async def spy(ctx, socket):
                seen["identity"] = Identity.from_socket(socket)
                await orig(ctx, socket)

            service.respond = spy
            await server.start()
            server.ctx.create_replica("topic", 0)
            policy = TlsPolicy(
                mode="verified",
                ca_cert=certs["ca"],
                domain="localhost",
                client_cert=certs["client_cert"],
                client_key=certs["client_key"],
            )
            client = await Fluvio.connect(_addr(server), tls=policy)
            producer = await client.topic_producer("topic")
            fut = await producer.send(None, b"hello")
            await producer.flush()
            await fut.wait()
            await client.close()
            await server.stop()

        asyncio.run(run())
        ident = seen["identity"]
        assert ident.principal == "alice"
        assert ident.scopes == ["admins"]

    def test_identity_without_cert_is_anonymous(self):
        assert Identity.from_peer_cert(None).principal == "anonymous"
        cert = {"subject": ((("commonName", "bob"),), (("organizationName", "ops"),))}
        ident = Identity.from_peer_cert(cert)
        assert ident.principal == "bob" and ident.scopes == ["ops"]
