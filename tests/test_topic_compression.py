"""Topic compression_type resolution on the produce path
(parity: TopicSpec.compression_type, topic/spec.rs; the reference
producer adopts the topic codec and refuses a conflicting explicit one).
"""

from __future__ import annotations

import asyncio

import pytest

from fluvio_tpu.client import ConsumerConfig, Fluvio, Offset
from fluvio_tpu.client.producer import (
    ProducerConfig,
    resolve_topic_compression,
)
from fluvio_tpu.metadata.topic import TopicSpec
from fluvio_tpu.protocol.compression import Compression
from fluvio_tpu.protocol.error import FluvioError

from test_sc import boot_cluster, shutdown_cluster


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


class TestResolution:
    def test_any_keeps_producer_choice(self):
        cfg = resolve_topic_compression("any", ProducerConfig(compression=Compression.GZIP))
        assert cfg.compression == Compression.GZIP
        assert resolve_topic_compression("any", None).compression is None

    def test_specific_adopted_when_unset(self):
        cfg = resolve_topic_compression("gzip", ProducerConfig())
        assert cfg.compression == Compression.GZIP

    def test_matching_explicit_ok(self):
        cfg = resolve_topic_compression(
            "gzip", ProducerConfig(compression=Compression.GZIP)
        )
        assert cfg.compression == Compression.GZIP

    def test_conflict_raises(self):
        with pytest.raises(FluvioError) as e:
            resolve_topic_compression(
                "gzip", ProducerConfig(compression=Compression.ZSTD)
            )
        assert "conflicts" in str(e.value)

    def test_caller_config_never_mutated(self):
        shared = ProducerConfig(batch_size=123)
        out = resolve_topic_compression("gzip", shared)
        assert out.compression == Compression.GZIP and out.batch_size == 123
        assert shared.compression is None  # reusable on the next topic

    def test_invalid_topic_codec_is_typed_error(self):
        with pytest.raises(FluvioError) as e:
            resolve_topic_compression("britli", ProducerConfig())
        assert "unknown compression" in str(e.value)


class TestEndToEnd:
    def test_topic_codec_rides_produce_and_consume(self, tmp_path):
        async def body():
            sc, admin, spus = await boot_cluster(tmp_path)
            spec = TopicSpec.computed(1)
            spec.compression_type = "gzip"
            await admin.create_topic("gz", spec)
            for _ in range(100):
                if spus[0].ctx.leader_for("gz", 0) is not None:
                    break
                await asyncio.sleep(0.05)
            client = await Fluvio.connect(sc.public_addr)
            try:
                # unset producer adopts gzip from the topic spec
                producer = await client.topic_producer("gz")
                fut = await producer.send(None, b"compressed-payload" * 10)
                await producer.flush()
                await fut.wait()
                await producer.close()

                # stored batch is actually gzip on disk
                from fluvio_tpu.schema.spu import Isolation

                leader = spus[0].ctx.leader_for("gz", 0)
                rslice = leader.read_records(
                    0, 1 << 20, Isolation.READ_UNCOMMITTED
                )
                batches = rslice.decode_batches(parse_records=False)
                assert batches[0].header.compression() == Compression.GZIP

                # consumers read it back transparently
                consumer = await client.partition_consumer("gz", 0)
                got = [
                    r.value
                    async for r in consumer.stream(
                        Offset.beginning(), ConsumerConfig(disable_continuous=True)
                    )
                ]
                assert got == [b"compressed-payload" * 10]

                # an explicitly conflicting producer codec is refused
                with pytest.raises(FluvioError):
                    await client.topic_producer(
                        "gz", config=ProducerConfig(compression=Compression.ZSTD)
                    )
            finally:
                await client.close()
                await shutdown_cluster(sc, admin, spus)

        run(body())
