"""TPU backend vs python backend: golden-output equivalence.

The §4(b)-style gate from SURVEY.md: the same chain on both engines must
produce byte-identical outputs on the baseline configs.
"""

import jax
import numpy as np
import pytest

from fluvio_tpu.models import lookup
from fluvio_tpu.protocol.record import Record
from fluvio_tpu.smartengine import SmartEngine, SmartModuleConfig
from fluvio_tpu.smartengine.engine import EngineError
from fluvio_tpu.smartmodule import SmartModuleInput


def build(backend, *mods):
    b = SmartEngine(backend=backend).builder()
    for module, config in mods:
        b.add_smart_module(config, module)
    return b.initialize()


def run_both(mods, records_fn):
    """Build both backends fresh and feed identical inputs; compare."""
    py = build("python", *mods)
    tpu = build("tpu", *mods)
    assert tpu.backend_in_use == "tpu"
    outs = []
    for records, base_offset, base_ts in records_fn():
        inp1 = SmartModuleInput.from_records(records, base_offset, base_ts)
        records2 = [
            Record(
                value=r.value, key=r.key,
                offset_delta=r.offset_delta, timestamp_delta=r.timestamp_delta,
            )
            for r in records
        ]
        inp2 = SmartModuleInput.from_records(records2, base_offset, base_ts)
        out_py = py.process(inp1)
        out_tpu = tpu.process(inp2)
        assert out_py.error is None and out_tpu.error is None
        got_py = [
            (r.key, r.value, r.offset_delta, r.timestamp_delta)
            for r in out_py.successes
        ]
        got_tpu = [
            (r.key, r.value, r.offset_delta, r.timestamp_delta)
            for r in out_tpu.successes
        ]
        assert got_py == got_tpu
        outs.append(got_py)
    return outs


def recs(*values, deltas=None):
    records = [Record(value=v) for v in values]
    for i, r in enumerate(records):
        r.offset_delta = i
        if deltas:
            r.timestamp_delta = deltas[i]
    return records


class TestEquivalence:
    def test_regex_filter(self):
        def gen():
            yield recs(b"apple", b"banana", b"avocado", b"cherry"), 0, -1

        outs = run_both(
            [(lookup("regex-filter"), SmartModuleConfig(params={"regex": "^a"}))], gen
        )
        assert [v for (_, v, _, _) in outs[0]] == [b"apple", b"avocado"]

    def test_regex_filter_json_map_chain(self):
        """The north-star chain (baseline config #1+#2)."""

        def gen():
            yield recs(
                b'{"name":"fluvio","n":1}',
                b'{"name":"kafka","n":2}',
                b'{"name":"fluvio-tpu","n":3}',
                b"not json at all",
            ), 100, 5000

        outs = run_both(
            [
                (lookup("regex-filter"), SmartModuleConfig(params={"regex": "fluvio"})),
                (lookup("json-map"), SmartModuleConfig(params={"field": "name"})),
            ],
            gen,
        )
        assert [v for (_, v, _, _) in outs[0]] == [b"FLUVIO", b"FLUVIO-TPU"]
        assert [d for (_, _, d, _) in outs[0]] == [0, 2]  # offsets preserved

    def test_aggregate_sum_across_calls(self):
        def gen():
            yield recs(b"1", b"2", b"3"), 0, -1
            yield recs(b"10", b"-4"), 3, -1

        outs = run_both([(lookup("aggregate-sum"), SmartModuleConfig())], gen)
        assert [v for (_, v, _, _) in outs[0]] == [b"1", b"3", b"6"]
        assert [v for (_, v, _, _) in outs[1]] == [b"16", b"12"]

    def test_aggregate_with_seed(self):
        def gen():
            yield recs(b"5"), 0, -1

        outs = run_both(
            [(lookup("aggregate-sum"), SmartModuleConfig(initial_data=b"100"))], gen
        )
        assert [v for (_, v, _, _) in outs[0]] == [b"105"]

    def test_filter_then_aggregate(self):
        def gen():
            yield recs(b"keep 1", b"drop 2", b"keep 3"), 0, -1

        outs = run_both(
            [
                (lookup("regex-filter"), SmartModuleConfig(params={"regex": "keep"})),
                (lookup("aggregate-count"), SmartModuleConfig()),
            ],
            gen,
        )
        assert [v for (_, v, _, _) in outs[0]] == [b"1", b"2"]

    def test_word_count(self):
        def gen():
            yield recs(b"hello world", b"", b"a b  c"), 0, -1

        outs = run_both([(lookup("word-count"), SmartModuleConfig())], gen)
        assert [v for (_, v, _, _) in outs[0]] == [b"2", b"2", b"5"]

    def test_windowed_sum(self):
        def gen():
            yield recs(
                b"1", b"2", b"3", b"4", deltas=[0, 500, 1000, 1500]
            ), 0, 10_000
            # second slab continues the last window then opens a new one
            yield recs(b"5", b"6", deltas=[1600, 2100]), 4, 10_000

        outs = run_both(
            [(lookup("windowed-sum"), SmartModuleConfig(params={"window_ms": "1000"}))],
            gen,
        )
        assert [(k, v) for (k, v, _, _) in outs[0]] == [
            (b"10000", b"1"),
            (b"10000", b"3"),
            (b"11000", b"3"),
            (b"11000", b"7"),
        ]
        assert [(k, v) for (k, v, _, _) in outs[1]] == [
            (b"11000", b"12"),
            (b"12000", b"6"),
        ]

    def test_aggregate_max_min(self):
        def gen():
            yield recs(b"5", b"3", b"9", b"7"), 0, -1

        outs = run_both([(lookup("aggregate-max"), SmartModuleConfig())], gen)
        assert [v for (_, v, _, _) in outs[0]] == [b"5", b"5", b"9", b"9"]

    def test_keys_preserved_through_filter(self):
        def gen():
            records = [
                Record(value=b"al", key=b"k0"),
                Record(value=b"bx", key=None),
                Record(value=b"ay", key=b"k2"),
            ]
            for i, r in enumerate(records):
                r.offset_delta = i
            yield records, 0, -1

        outs = run_both(
            [(lookup("regex-filter"), SmartModuleConfig(params={"regex": "^a"}))], gen
        )
        assert [(k, v) for (k, v, _, _) in outs[0]] == [(b"k0", b"al"), (b"k2", b"ay")]

    def test_fuzz_northstar_chain(self):
        rng = np.random.default_rng(3)
        names = ["fluvio", "kafka", "pulsar", "fluvio-tpu", "x"]

        def gen():
            for base in (0, 1000):
                records = []
                for i in range(rng.integers(5, 40)):
                    name = names[rng.integers(0, len(names))]
                    n = rng.integers(0, 100)
                    records.append(Record(value=f'{{"name":"{name}","n":{n}}}'.encode()))
                for i, r in enumerate(records):
                    r.offset_delta = i
                yield records, base, -1

        run_both(
            [
                (lookup("regex-filter"), SmartModuleConfig(params={"regex": "fluvio"})),
                (lookup("json-map"), SmartModuleConfig(params={"field": "n"})),
            ],
            gen,
        )


class TestBackendSelection:
    def test_tpu_refuses_hook_only_module(self):
        src = "@smartmodule.filter\ndef f(record):\n    return True\n"
        b = SmartEngine(backend="tpu").builder()
        b.add_smart_module(SmartModuleConfig(), src)
        with pytest.raises(EngineError):
            b.initialize()

    def test_auto_falls_back_to_python(self):
        src = "@smartmodule.filter\ndef f(record):\n    return True\n"
        b = SmartEngine(backend="auto").builder()
        b.add_smart_module(SmartModuleConfig(), src)
        chain = b.initialize()
        assert chain.backend_in_use == "python"

    def test_auto_uses_tpu_for_dsl_chain(self):
        b = SmartEngine(backend="auto").builder()
        b.add_smart_module(
            SmartModuleConfig(params={"regex": "x"}), lookup("regex-filter")
        )
        chain = b.initialize()
        assert chain.backend_in_use == "tpu"

    def test_unsupported_regex_falls_back(self):
        """Backreferences can't become DFAs: auto skips the TPU backend
        and lands on a host engine (native when a toolchain exists)."""
        from fluvio_tpu.protocol.record import Record
        from fluvio_tpu.smartmodule.types import SmartModuleInput

        b = SmartEngine(backend="auto").builder()
        b.add_smart_module(
            SmartModuleConfig(params={"regex": r"(a)\1"}), lookup("regex-filter")
        )
        chain = b.initialize()
        assert chain.backend_in_use in ("python", "native")
        out = chain.process(
            SmartModuleInput.from_records(
                [Record(value=b"has aa here"), Record(value=b"only a")]
            )
        )
        assert [r.value for r in out.successes] == [b"has aa here"]


class TestWidthBuckets:
    """Value-matrix width buckets: padding is scan compute, so widths
    above 128 bucket at pow2/8 granularity (VERDICT r4 weak #3 — a
    300 B corpus runs 320 scan steps, not 512)."""

    def test_bucket_width_values(self):
        from fluvio_tpu.smartengine.tpu.buffer import bucket_width

        assert bucket_width(0) == 32
        assert bucket_width(33) == 64
        assert bucket_width(128) == 128
        assert bucket_width(129) == 160
        assert bucket_width(310) == 320
        assert bucket_width(505) == 512
        assert bucket_width(513) == 640

    def test_bucket_width_invariants(self):
        from fluvio_tpu.smartengine.tpu.buffer import bucket_width

        prev = 0
        for n in range(0, 5000, 7):
            w = bucket_width(n)
            assert w >= max(n, 32)
            assert w % 32 == 0 or w < 128
            assert w >= prev  # monotone: bigger records never shrink
            prev = w

    def test_wide_corpus_chain_equivalence(self):
        """300 B records (uint16 descriptor tier + non-pow2 width) stay
        byte-equal to the interpreter through the full chain."""
        from fluvio_tpu.protocol.record import Record
        from fluvio_tpu.smartmodule.types import SmartModuleInput

        pad = "p" * 240
        values = [
            f'{{"name":"fluvio-{i}","pad":"{pad}","n":{i}}}'.encode()
            for i in range(50)
        ]

        def run(backend):
            b = SmartEngine(backend=backend).builder()
            b.add_smart_module(
                SmartModuleConfig(params={"regex": "fluvio"}),
                lookup("regex-filter"),
            )
            b.add_smart_module(
                SmartModuleConfig(params={"field": "name"}), lookup("json-map")
            )
            chain = b.initialize()
            out = chain.process(
                SmartModuleInput.from_records(
                    [Record(value=v) for v in values]
                )
            )
            assert out.error is None
            return [r.value for r in out.successes]

        got = run("tpu")
        assert got == run("python")
        assert len(got) == 50


class TestDispatchPrefetch:
    """Dispatch-time speculative D2H (the tunnel-RTT diet).

    `dispatch_buffer` starts the header/mask copies and — once two
    consecutive batches agree on a survivor bucket — the viewable
    descriptor slices, speculatively. A stream whose survivor counts
    shift buckets mid-flight must stay byte-correct through both the
    hit and the miss path, and the miss must charge the wasted bytes
    to the D2H counter.
    """

    def _bufs(self, counts):
        from fluvio_tpu.smartengine.tpu.buffer import RecordBuffer

        out = []
        for match_n in counts:
            records = [
                Record(
                    value=(
                        b'{"name":"fluvio-%d"}' % i
                        if i < match_n
                        else b'{"name":"drop-%d"}' % i
                    )
                )
                for i in range(256)
            ]
            for i, r in enumerate(records):
                r.offset_delta = i
            out.append(RecordBuffer.from_records(records))
        return out

    def _chain(self, backend):
        # filter + span-map: descriptor speculation only exists for
        # view chains with real descriptors (a filter-only chain rides
        # the identity path, where the mask is the whole download)
        return build(
            backend,
            (lookup("regex-filter"), SmartModuleConfig(params={"regex": "fluvio"})),
            (lookup("json-map"), SmartModuleConfig(params={"field": "name"})),
        )

    def test_stream_correct_across_bucket_shift(self):
        # the pipelined stream dispatches one batch ahead of the
        # finishes that feed the bucket history, so arming lags one
        # batch: the 40-run hits from its 4th dispatch, the 40->200
        # shift misses twice (stale guess, then disagreeing history),
        # and the 200-run re-arms and hits at its 4th batch
        counts = [40, 40, 40, 40, 40, 200, 200, 200, 200]
        tpu = self._chain("tpu").tpu_chain
        piped = [
            [r.value for r in out.to_records()]
            for out in tpu.process_stream(iter(self._bufs(counts)))
        ]
        py = self._chain("python")
        for vals, buf in zip(piped, self._bufs(counts)):
            out = py.process(
                SmartModuleInput.from_records(buf.to_records())
            )
            assert vals == [r.value for r in out.successes]

    def test_spec_arms_hits_and_charges_misses(self):
        tpu = self._chain("tpu").tpu_chain
        b40, b200 = self._bufs([40, 200])

        h = tpu.dispatch_buffer(b40)
        assert "view" not in h[3]  # cold: no guess yet
        tpu.finish_buffer(b40, h)
        h = tpu.dispatch_buffer(b40)
        assert "view" not in h[3]  # one observation: not armed yet
        tpu.finish_buffer(b40, h)

        h = tpu.dispatch_buffer(b40)
        assert "view" in h[3]  # two agreeing buckets: armed
        rows_guess = h[3]["view"][0]
        hit_spec = h[3]["view"]
        d2h_before = tpu.d2h_bytes_total
        out = tpu.finish_buffer(b40, h)  # hit: same bucket
        # the hit path must return the right BYTES (the prefetched
        # descriptor slices drive the host-side value rebuild) ...
        assert [r.value for r in out.to_records()] == [
            b"FLUVIO-%d" % i for i in range(40)  # json-map uppercases
        ]
        # ... and download the prefetched slices exactly once
        hit_delta = tpu.d2h_bytes_total - d2h_before
        assert hit_delta >= hit_spec[1].nbytes + hit_spec[2].nbytes
        assert hit_delta < 2 * (hit_spec[1].nbytes + hit_spec[2].nbytes) + 4096

        h = tpu.dispatch_buffer(b200)
        assert "view" in h[3]
        spec = h[3]["view"]
        d2h_before = tpu.d2h_bytes_total
        tpu.finish_buffer(b200, h)  # miss: bucket shifted
        wasted = spec[1].nbytes + spec[2].nbytes
        assert tpu.d2h_bytes_total - d2h_before >= wasted
        assert tpu._spec_rows != rows_guess


class TestTransferGuardArm:
    """ISSUE-7 tier-1 arm: with ``FLUVIO_TRANSFER_GUARD=disallow`` the
    executor runs every dispatch-side region under
    ``jax.transfer_guard_device_to_host("disallow")`` while the
    intentional fetch/d2h seam stays on an explicit allow scope. On an
    accelerator an implicit D2H raises at the offending line; on the
    host-resident CPU backend the scopes are structurally exercised and
    these tests pin the seam selection itself."""

    def test_unarmed_seams_are_shared_nullcontext(self, monkeypatch):
        from fluvio_tpu.smartengine.tpu import executor as ex

        monkeypatch.delenv("FLUVIO_TRANSFER_GUARD", raising=False)
        assert ex.transfer_guard_dispatch() is ex._NULL_CTX
        assert ex.transfer_guard_fetch() is ex._NULL_CTX
        # explicit off-spellings disarm BOTH seams consistently
        for off in ("0", "off", "none", "allow", " OFF "):
            monkeypatch.setenv("FLUVIO_TRANSFER_GUARD", off)
            assert ex.transfer_guard_dispatch() is ex._NULL_CTX
            assert ex.transfer_guard_fetch() is ex._NULL_CTX

    def test_invalid_mode_rejected_loudly(self, monkeypatch):
        """A typo'd arm must not silently half-arm the guard (dispatch
        unguarded while fetch enters the allow scope)."""
        from fluvio_tpu.smartengine.tpu import executor as ex

        monkeypatch.setenv("FLUVIO_TRANSFER_GUARD", "disalow")
        with pytest.raises(ValueError, match="FLUVIO_TRANSFER_GUARD"):
            ex.transfer_guard_dispatch()
        with pytest.raises(ValueError, match="FLUVIO_TRANSFER_GUARD"):
            ex.transfer_guard_fetch()

    def test_armed_scopes_select_guard_modes(self, monkeypatch):
        from jax._src import config as jcfg

        from fluvio_tpu.smartengine.tpu import executor as ex

        monkeypatch.setenv("FLUVIO_TRANSFER_GUARD", "disallow")
        with ex.transfer_guard_dispatch():
            assert jcfg.transfer_guard_device_to_host.value == "disallow"
            # the allowlist: the fetch seam re-opens D2H even inside an
            # armed dispatch scope (and under a process-global arm)
            with ex.transfer_guard_fetch():
                assert jcfg.transfer_guard_device_to_host.value == "allow"
            assert jcfg.transfer_guard_device_to_host.value == "disallow"

    def _spy_seams(self, monkeypatch):
        """Record the ACTIVE guard mode at entry to the real dispatch
        and fetch bodies during live traffic."""
        from jax._src import config as jcfg

        from fluvio_tpu.smartengine.tpu.executor import TpuChainExecutor

        seen = {"dispatch": set(), "fetch": set()}
        orig_dispatch = TpuChainExecutor._dispatch_inner
        orig_fetch = TpuChainExecutor._fetch_inner

        def spy_dispatch(self, *a, **k):
            seen["dispatch"].add(jcfg.transfer_guard_device_to_host.value)
            return orig_dispatch(self, *a, **k)

        def spy_fetch(self, *a, **k):
            seen["fetch"].add(jcfg.transfer_guard_device_to_host.value)
            return orig_fetch(self, *a, **k)

        monkeypatch.setattr(TpuChainExecutor, "_dispatch_inner", spy_dispatch)
        monkeypatch.setattr(TpuChainExecutor, "_fetch_inner", spy_fetch)
        return seen

    def test_fused_path_clean_under_disallow(self, monkeypatch):
        monkeypatch.setenv("FLUVIO_TRANSFER_GUARD", "disallow")
        seen = self._spy_seams(monkeypatch)
        mods = [
            (lookup("regex-filter"), SmartModuleConfig(params={"regex": "fluvio"})),
            (lookup("json-map"), SmartModuleConfig(params={"field": "name"})),
        ]

        def gen():
            yield recs(
                b'{"name":"fluvio-a","n":1}',
                b'{"name":"kafka-b","n":2}',
                b'{"name":"fluvio-c","n":3}',
            ), 0, 0

        run_both([(m, c) for m, c in mods], gen)
        assert seen["dispatch"] == {"disallow"}
        assert seen["fetch"] == {"allow"}

    def test_striped_path_clean_under_disallow(self, monkeypatch):
        """The striped lowering's dispatch runs under the same guard
        scope (stripe gates forced low so a ~300 B corpus stripes)."""
        monkeypatch.setenv("FLUVIO_TRANSFER_GUARD", "disallow")
        monkeypatch.setenv("FLUVIO_STRIPE_THRESHOLD", "64")
        monkeypatch.setenv("FLUVIO_STRIPE_WIDTH", "64")
        monkeypatch.setenv("FLUVIO_STRIPE_OVERLAP", "16")
        seen = self._spy_seams(monkeypatch)
        pad = "p" * 240
        values = [
            f'{{"name":"fluvio-{i}","pad":"{pad}","n":{i}}}'.encode()
            for i in range(40)
        ]

        def run(backend):
            chain = build(
                backend,
                (lookup("regex-filter"),
                 SmartModuleConfig(params={"regex": "fluvio"})),
                (lookup("json-map"),
                 SmartModuleConfig(params={"field": "name"})),
            )
            out = chain.process(
                SmartModuleInput.from_records(
                    [Record(value=v) for v in values]
                )
            )
            assert out.error is None
            return [r.value for r in out.successes]

        tpu_chain = build(
            "tpu",
            (lookup("regex-filter"),
             SmartModuleConfig(params={"regex": "fluvio"})),
            (lookup("json-map"),
             SmartModuleConfig(params={"field": "name"})),
        )
        assert tpu_chain.tpu_chain._striped_chain() is not None
        got = run("tpu")
        assert got == run("python")
        assert len(got) == 40
        assert seen["dispatch"] == {"disallow"}
        assert seen["fetch"] == {"allow"}

    def _spy_sharded_seams(self, monkeypatch):
        """Record the ACTIVE guard mode inside the sharded delegate's
        dispatch and finish bodies. The dispatch spy hooks
        `_dispatch_buffer_inner` — `dispatch_buffer` enters the guard
        scope itself, so the mode INSIDE the body is the invariant,
        whatever scope the caller was in."""
        from jax._src import config as jcfg

        from fluvio_tpu.parallel.sharded import ShardedChainExecutor

        seen = {"dispatch": [], "finish": []}
        orig_dispatch = ShardedChainExecutor._dispatch_buffer_inner
        orig_finish = ShardedChainExecutor.finish_buffer

        def spy_dispatch(self, *a, **k):
            seen["dispatch"].append(jcfg.transfer_guard_device_to_host.value)
            return orig_dispatch(self, *a, **k)

        def spy_finish(self, *a, **k):
            seen["finish"].append(jcfg.transfer_guard_device_to_host.value)
            return orig_finish(self, *a, **k)

        monkeypatch.setattr(
            ShardedChainExecutor, "_dispatch_buffer_inner", spy_dispatch
        )
        monkeypatch.setattr(ShardedChainExecutor, "finish_buffer", spy_finish)
        return seen

    @pytest.mark.skipif(
        len(jax.devices()) < 8, reason="needs 8 virtual devices"
    )
    def test_sharded_path_clean_under_disallow(self, monkeypatch):
        """The sharded delegate's dispatch runs under the dispatch
        guard; only the finish/download half sees the allow seam."""
        monkeypatch.setenv("FLUVIO_TRANSFER_GUARD", "disallow")
        seen = self._spy_sharded_seams(monkeypatch)
        chain = build(
            "tpu",
            (lookup("regex-filter"),
             SmartModuleConfig(params={"regex": "fluvio"})),
        )
        ex = chain.tpu_chain
        ex.enable_sharded(8)
        values = [
            (f'fluvio-{i}' if i % 2 else f'kafka-{i}').encode()
            for i in range(64)
        ]
        inp = SmartModuleInput.from_records([Record(value=v) for v in values])
        out = chain.process(inp)
        assert out.error is None and len(out.successes) == 32
        assert set(seen["dispatch"]) == {"disallow"}
        assert set(seen["finish"]) == {"allow"}

    @pytest.mark.skipif(
        len(jax.devices()) < 8, reason="needs 8 virtual devices"
    )
    def test_sharded_direct_process_buffer_guarded(self, monkeypatch):
        """Regression: `ShardedChainExecutor.process_buffer` drives
        `dispatch_buffer` with no executor delegation in between — the
        guard scope lives inside `dispatch_buffer`, so the direct
        entry point dispatches guarded too."""
        from fluvio_tpu.smartengine.tpu.buffer import RecordBuffer

        monkeypatch.setenv("FLUVIO_TRANSFER_GUARD", "disallow")
        seen = self._spy_sharded_seams(monkeypatch)
        chain = build(
            "tpu",
            (lookup("regex-filter"),
             SmartModuleConfig(params={"regex": "fluvio"})),
        )
        ex = chain.tpu_chain
        ex.enable_sharded(8)
        records = [
            Record(value=(f'fluvio-{i}' if i % 2 else f'kafka-{i}').encode())
            for i in range(64)
        ]
        for i, r in enumerate(records):
            r.offset_delta = i
        buf = RecordBuffer.from_records(
            records, base_offset=0, base_timestamp=1000
        )
        out = ex._sharded.process_buffer(buf)
        assert len(out.to_records()) == 32
        assert set(seen["dispatch"]) == {"disallow"}

    @pytest.mark.skipif(
        len(jax.devices()) < 8, reason="needs 8 virtual devices"
    )
    def test_sharded_retry_redispatch_stays_guarded(self, monkeypatch):
        """Regression: the transient-retry re-dispatch inside
        `_finish_sharded_inner` fires from within the fetch ALLOW scope
        — it must re-enter the dispatch guard, not inherit the
        allowlist (an implicit D2H during a retry is exactly the class
        the arm exists to reject)."""
        from fluvio_tpu.resilience import faults

        monkeypatch.setenv("FLUVIO_TRANSFER_GUARD", "disallow")
        monkeypatch.setenv("FLUVIO_RETRY_BASE_MS", "0")
        seen = self._spy_sharded_seams(monkeypatch)
        chain = build(
            "tpu",
            (lookup("regex-filter"),
             SmartModuleConfig(params={"regex": "fluvio"})),
        )
        ex = chain.tpu_chain
        ex.enable_sharded(8)
        faults.FAULTS.inject("device", first=1)
        try:
            inp = SmartModuleInput.from_records(
                [Record(value=b"fluvio-x")] * 64
            )
            out = chain.process(inp)
        finally:
            faults.FAULTS.clear()
        assert out.error is None and len(out.successes) == 64
        # initial dispatch + the retry re-dispatch: BOTH under disallow
        assert len(seen["dispatch"]) == 2
        assert set(seen["dispatch"]) == {"disallow"}
        # the failed finish attempt and its retry both ran on the seam
        assert len(seen["finish"]) == 2
        assert set(seen["finish"]) == {"allow"}
