"""TPU kernel equivalence vs the pinned DSL byte semantics (CPU jax)."""

import numpy as np
import pytest

from fluvio_tpu.smartmodule import dsl
from fluvio_tpu.smartengine.tpu import kernels
from fluvio_tpu.smartengine.tpu.buffer import RecordBuffer
from fluvio_tpu.ops.regex_dfa import compile_regex
from fluvio_tpu.protocol.record import Record


def stage(values_list):
    buf = RecordBuffer.from_records([Record(value=v) for v in values_list])
    return buf


JSON_DOCS = [
    b'{"name":"fluvio"}',
    b'{"a":1,"name":"x"}',
    b'{"name": "spaced" }',
    b'{"name":42}',
    b'{"name":-3.5,"z":1}',
    b'{"name":true}',
    b'{"name":null}',
    b'{"name":{"inner":1}}',
    b'{"name":[1,2]}',
    b'{"other":"x"}',
    b"not json",
    b"",
    b'{"nested":{"name":"inner"},"name":"outer"}',
    b'{"val":"name","name":"real"}',
    b'{"namer":"no","name":"yes"}',
    b'{"outer":{"name":"inner"}}',
    b'{"name":""}',
    b'{"x":[{"name":"in-array"}],"name":"top"}',
    b'{"name":"unterminated',
    b'{"name":  12345  ,"q":2}',
    b'{ "padded" : 1, "name" : "v" }',
    b'{"name":"with \\"escape\\""}',
]


class TestJsonGet:
    @pytest.mark.parametrize("key", ["name", "q", ""])
    @pytest.mark.parametrize("fn", [kernels.json_get, kernels.json_get_parallel])
    def test_matches_reference(self, key, fn):
        buf = stage(JSON_DOCS)
        out_v, out_l = fn(buf.values, buf.lengths, key)
        out_v = np.asarray(out_v)
        out_l = np.asarray(out_l)
        for i, doc in enumerate(JSON_DOCS):
            expected = dsl.json_get_bytes(doc, key)
            got = out_v[i, : out_l[i]].tobytes()
            assert got == expected, f"doc={doc!r} key={key!r}: {got!r} != {expected!r}"

    def test_fuzz_random_json(self):
        rng = np.random.default_rng(7)
        keys = ["a", "bb", "name"]
        docs = []
        for _ in range(200):
            parts = []
            for k in rng.choice(keys, size=rng.integers(1, 4), replace=False):
                kind = rng.integers(0, 4)
                if kind == 0:
                    v = f'"{rng.integers(0, 999)}"'
                elif kind == 1:
                    v = str(rng.integers(-5000, 5000))
                elif kind == 2:
                    v = '{"in":' + str(rng.integers(0, 9)) + "}"
                else:
                    v = "[1,2,3]"
                parts.append(f'"{k}":{v}')
            docs.append(("{" + ",".join(parts) + "}").encode())
        buf = stage(docs)
        for fn in (kernels.json_get, kernels.json_get_parallel):
          for key in keys:
            out_v, out_l = fn(buf.values, buf.lengths, key)
            out_v, out_l = np.asarray(out_v), np.asarray(out_l)
            for i, doc in enumerate(docs):
                expected = dsl.json_get_bytes(doc, key)
                assert out_v[i, : out_l[i]].tobytes() == expected, (doc, key)


class TestJsonParallelExactness:
    """The structural-index kernel is now the DEFAULT XLA span path: its
    string/escape tracking runs on the exact 3-state automaton
    (kernels.string_state_excl, transition composition) instead of the
    backslash-run parity heuristic — so it must match the scan kernel
    AND the DSL reference on arbitrary structural garbage, including the
    heuristic's old escaped-quote-outside-strings deviation."""

    def test_old_deviation_shapes(self):
        docs = [
            b'\\"name":1}',        # backslash before a quote, outside any string
            b'{\\\\"name":2}',
            b'{\\"name":"v"}',
            b'{"a":"b\\\\","name":"c"}',
            b'{"name":"a\\"b"}',   # escape inside a string (both paths agree)
            b'{"na\\"me":"x","name":"y"}',
            b'{"name":"\\\\"}',
        ]
        buf = stage(docs)
        for key in ("name", "a"):
            st_s, ln_s = kernels.json_get_span(buf.values, buf.lengths, key)
            st_p, ln_p = kernels.json_get_parallel_span(
                buf.values, buf.lengths, key
            )
            for i, d in enumerate(docs):
                a = d[int(st_s[i]) : int(st_s[i]) + int(ln_s[i])]
                b = d[int(st_p[i]) : int(st_p[i]) + int(ln_p[i])]
                ref = dsl.json_get_bytes(d, key) or b""
                assert a == b == ref, (d, key, a, b, ref)

    def test_fuzz_structural_garbage(self):
        rng = np.random.default_rng(99)
        alphabet = list(b'{}[]":\\, abn0123x')
        docs = [
            bytes(
                rng.choice(alphabet, size=rng.integers(1, 70)).astype(np.uint8)
            )
            for _ in range(600)
        ]
        buf = stage(docs)
        for key in ("name", "a"):
            st_s, ln_s = kernels.json_get_span(buf.values, buf.lengths, key)
            st_p, ln_p = kernels.json_get_parallel_span(
                buf.values, buf.lengths, key
            )
            st_s, ln_s = np.asarray(st_s), np.asarray(ln_s)
            st_p, ln_p = np.asarray(st_p), np.asarray(ln_p)
            for i, d in enumerate(docs):
                a = d[st_s[i] : st_s[i] + ln_s[i]]
                b = d[st_p[i] : st_p[i] + ln_p[i]]
                ref = dsl.json_get_bytes(d, key) or b""
                assert a == b == ref, (d, key, a, b, ref)


class TestParseInt:
    def test_matches_reference(self):
        cases = [b"42", b"-7", b"  13x", b"+5", b"abc", b"", b"12.9", b"-",
                 b"9223372036854775807", b"  -00042  ", b"1e5", b"0"]
        buf = stage(cases)
        got = np.asarray(kernels.parse_int(buf.values, buf.lengths))
        for i, c in enumerate(cases):
            assert got[i] == dsl.parse_int_prefix(c), c


class TestIntToAscii:
    def test_matches_str(self):
        xs = np.array(
            [0, 1, -1, 9, 10, -10, 12345, -987654321,
             2**62, -(2**62), 2**63 - 1, -(2**63)],
            dtype=np.int64,
        )
        import jax.numpy as jnp

        out_v, out_l = kernels.int_to_ascii(jnp.asarray(xs))
        out_v, out_l = np.asarray(out_v), np.asarray(out_l)
        for i, x in enumerate(xs.tolist()):
            assert out_v[i, : out_l[i]].tobytes() == str(x).encode(), x


class TestCase:
    def test_upper_lower(self):
        buf = stage([b"aZ3{}", b"Hello World!"])
        up = np.asarray(kernels.ascii_upper(buf.values))
        lo = np.asarray(kernels.ascii_lower(buf.values))
        assert up[0, :5].tobytes() == b"AZ3{}"
        assert lo[1, :12].tobytes() == b"hello world!"


class TestCountWords:
    def test_matches_split(self):
        cases = [b"hello world", b"", b"  a  ", b"one two  three", b"\tx\ny z\r"]
        buf = stage(cases)
        got = np.asarray(kernels.count_words(buf.values, buf.lengths))
        for i, c in enumerate(cases):
            assert got[i] == len(c.split()), c


class TestDfaMatchJax:
    def test_matches_numpy_matcher(self):
        import re

        corpus = [b"abc", b"xabcx", b"", b"ab", b"zzzabczzz", b"a" * 31, b"xyz"]
        for pattern in ["abc", "^abc", "abc$", "a+b", "[a-y]+$", r"\d"]:
            dfa = compile_regex(pattern)
            buf = stage(corpus)
            got = np.asarray(kernels.dfa_match(buf.values, buf.lengths, dfa))
            rx = re.compile(pattern.encode())
            for i, data in enumerate(corpus):
                assert got[i] == (rx.search(data) is not None), (pattern, data)


class TestSegmentedScan:
    def test_sum_with_resets(self):
        import jax.numpy as jnp

        x = jnp.asarray(np.array([1, 2, 3, 4, 5], dtype=np.int64))
        reset = jnp.asarray(np.array([True, False, True, False, False]))
        out = np.asarray(kernels.segmented_scan(x, reset, "add"))
        np.testing.assert_array_equal(out, [1, 3, 3, 7, 12])

    def test_max(self):
        import jax.numpy as jnp

        x = jnp.asarray(np.array([3, 1, 5, 2], dtype=np.int64))
        reset = jnp.asarray(np.array([True, False, True, False]))
        out = np.asarray(kernels.segmented_scan(x, reset, "max"))
        np.testing.assert_array_equal(out, [3, 3, 5, 5])

    def test_propagate_last_valid(self):
        import jax.numpy as jnp

        vals = jnp.asarray(np.array([10, 20, 30, 40], dtype=np.int64))
        valid = jnp.asarray(np.array([False, True, False, True]))
        filled, has = kernels.propagate_last_valid(vals, valid)
        np.testing.assert_array_equal(np.asarray(filled)[1:], [20, 20, 40])
        np.testing.assert_array_equal(np.asarray(has), [False, True, True, True])

    def test_compact_rows(self):
        import jax.numpy as jnp

        mask = jnp.asarray(np.array([True, False, True, False]))
        vals = jnp.asarray(np.arange(8, dtype=np.int64).reshape(4, 2))
        count, (packed,) = kernels.compact_rows(mask, vals)
        assert int(count) == 2
        np.testing.assert_array_equal(np.asarray(packed)[:2], [[0, 1], [4, 5]])


class TestLiteralSearch:
    def test_matches_python(self):
        corpus = [b"", b"abc", b"xabcx", b"ab", b"aabbcc", b"abcabc", b"zzabc"]
        buf = stage(corpus)
        for lit in [b"abc", b"", b"z", b"abcd", b"aa"]:
            got = np.asarray(kernels.literal_search(buf.values, buf.lengths, lit))
            starts = np.asarray(kernels.literal_startswith(buf.values, buf.lengths, lit))
            ends = np.asarray(kernels.literal_endswith(buf.values, buf.lengths, lit))
            for i, data in enumerate(corpus):
                assert got[i] == (lit in data), (lit, data)
                assert starts[i] == data.startswith(lit), (lit, data)
                assert ends[i] == data.endswith(lit), (lit, data)


def test_fast_scan_paths_match_tuple_scans():
    # segmented_scan's add fast path (cumsum - base) and the cummax
    # forward-fill must stay bit-equal to the tuple-carry
    # associative_scan they replaced (the aggregate engine's semantics)
    import numpy as np
    import jax.numpy as jnp
    from jax import lax

    from fluvio_tpu.smartengine.tpu import kernels

    rng = np.random.default_rng(3)
    for trial in range(15):
        n = int(rng.integers(1, 300))
        x = jnp.asarray(rng.integers(-10**12, 10**12, n))
        reset = jnp.asarray(rng.random(n) < rng.random())

        def combine(a, b):
            fa, va = a
            fb, vb = b
            return fa | fb, jnp.where(fb, vb, va + vb)

        _, want = lax.associative_scan(combine, (reset, x))
        got = kernels.segmented_scan(x, reset, "add")
        assert np.array_equal(np.asarray(want), np.asarray(got)), trial

        vals = jnp.asarray(rng.integers(0, 10**9, n))
        valid = jnp.asarray(rng.random(n) < rng.random())

        def pcomb(a, b):
            ha, va = a
            hb, vb = b
            return ha | hb, jnp.where(hb, vb, va)

        whas, wfill = lax.associative_scan(pcomb, (valid, vals))
        gfill, ghas = kernels.propagate_last_valid(vals, valid)
        assert np.array_equal(np.asarray(wfill), np.asarray(gfill)), trial
        assert np.array_equal(np.asarray(whas), np.asarray(ghas)), trial
