"""Engine flight recorder (ISSUE-5): Perfetto trace export, JIT-compile
telemetry, and device-memory/queue gauges.

Covers the acceptance surfaces:

- trace round-trip: the rendered document is valid Chrome-trace JSON,
  batch-event parity with `spans_json()`, overlapping batches land on
  distinct tracks (the pipelined overlap is visible), phases sit at
  their recorded wall positions,
- the continuous `FLUVIO_TRACE` file sink stays valid JSON after every
  append and respects its rotation bound,
- compile events on a forced fresh shape bucket (counts, seconds,
  trace-cache hit accounting, DFA table builds) and the recompile-storm
  decline,
- gauge up/down correctness across dispatch/finish/discard including
  the sharded path, the dead-letter occupancy gauge, and the pipelined
  queue-depth release idempotence,
- `SpanRing.dropped` through snapshot + Prometheus,
- the monitoring socket's ``trace`` mode and the `fluvio-tpu trace`
  CLI.
"""

from __future__ import annotations

import asyncio
import json
import os

import jax
import numpy as np
import pytest

from fluvio_tpu.models import lookup
from fluvio_tpu.protocol.record import Record
from fluvio_tpu.smartengine import SmartEngine, SmartModuleConfig
from fluvio_tpu.smartengine.tpu.buffer import RecordBuffer
from fluvio_tpu.telemetry import (
    TELEMETRY,
    PipelineTelemetry,
    TraceFileSink,
    render_prometheus,
    render_trace,
)
from fluvio_tpu.telemetry.spans import BatchSpan, InstantEvent, SpanRing
from fluvio_tpu.telemetry import trace as trace_mod


@pytest.fixture(autouse=True)
def _fresh_registry():
    TELEMETRY.reset()
    prior = TELEMETRY.enabled
    TELEMETRY.enabled = True
    yield
    TELEMETRY.enabled = prior
    TELEMETRY.trace_sink = None
    TELEMETRY.reset()


def _span(t0: float, dur: float, path: str = "fused", records: int = 8):
    s = BatchSpan(path)
    s.t0 = t0
    s.t_end = t0 + dur
    s.records = records
    return s


def _chain(*specs):
    b = SmartEngine(backend="tpu").builder()
    for name, params in specs:
        b.add_smart_module(SmartModuleConfig(params=params or {}), lookup(name))
    chain = b.initialize()
    assert chain.backend_in_use == "tpu"
    return chain


def _buf(n: int = 64, tag: str = "fluvio"):
    records = [
        Record(value=f'{{"name":"{tag}-{i}","n":{i}}}'.encode())
        for i in range(n)
    ]
    for i, r in enumerate(records):
        r.offset_delta = i
    return RecordBuffer.from_records(records)


# ---------------------------------------------------------------------------
# trace document
# ---------------------------------------------------------------------------


class TestTraceDocument:
    def test_round_trip_parity_and_overlap_tracks(self):
        # two overlapping fused batches (the pipelined shape) + one after
        a = _span(100.0, 0.010)
        a.phase_s[0] = 0.002  # stage
        a.phase_t0[0] = 100.0
        a.phase_s[4] = 0.006  # device
        a.phase_t0[4] = 100.003
        b = _span(100.005, 0.010)
        c = _span(100.020, 0.005, path="striped")
        for s in (a, b, c):
            TELEMETRY.spans.push(s)
        doc = json.loads(json.dumps(render_trace()))
        events = doc["traceEvents"]
        batches = [e for e in events if e.get("cat") == "batch"]
        # event parity: one batch envelope per retained span
        assert len(batches) == len(TELEMETRY.spans_json()) == 3
        # the overlapping pair occupies two DISTINCT tracks
        fused_tids = {
            e["tid"] for e in batches if e["args"]["path"] == "fused"
        }
        assert len(fused_tids) == 2
        # striped batches live in their own track family
        striped = [e for e in batches if e["args"]["path"] == "striped"]
        assert striped and striped[0]["tid"] not in fused_tids
        # phases are duration events at their recorded wall positions
        phases = {e["name"]: e for e in events if e.get("cat") == "phase"}
        assert phases["stage"]["dur"] == pytest.approx(2000, rel=0.01)
        assert phases["device"]["ts"] > phases["stage"]["ts"]
        # the envelope spans its phases
        env = [e for e in batches if e["ts"] == 0.0][0]
        assert env["dur"] == pytest.approx(10000, rel=0.01)

    def test_instant_events_render_as_markers(self):
        TELEMETRY.end_batch(TELEMETRY.begin_batch(), records=4)
        TELEMETRY.add_heal()
        TELEMETRY.add_spill("transform-error")
        TELEMETRY.add_retry("fetch")
        TELEMETRY.record_breaker("chain-a", "open")
        TELEMETRY.add_compile("ragged", "sig w=32", 0.5, True)
        doc = render_trace()
        marks = [e for e in doc["traceEvents"] if e.get("ph") == "i"]
        kinds = {e["name"] for e in marks}
        assert {"heal", "spill", "retry", "breaker", "compile"} <= kinds
        spill = [e for e in marks if e["name"] == "spill"][0]
        assert spill["args"]["detail"] == "transform-error"

    def test_empty_registry_renders_valid_doc(self):
        doc = json.loads(json.dumps(render_trace()))
        assert doc["traceEvents"]  # metadata only, still loadable


# ---------------------------------------------------------------------------
# continuous file sink
# ---------------------------------------------------------------------------


class TestTraceFileSink:
    def test_file_always_valid_json_with_event_parity(self, tmp_path):
        path = str(tmp_path / "t.json")
        sink = TraceFileSink(path, max_bytes=1 << 20)
        assert not os.path.exists(path)  # lazy: no file until a write
        n = 17
        for i in range(n):
            sink.on_span(_span(10.0 + i * 0.01, 0.005, records=i))
            sink.flush()
            # the on-disk content is valid JSON after EVERY write
            data = json.load(open(path))
        sink.on_event(InstantEvent("heal"))
        sink.flush()  # force the coalesced tail out before asserting
        data = json.load(open(path))
        batches = [e for e in data if e.get("cat") == "batch"]
        assert len(batches) == n
        assert any(e.get("ph") == "i" and e["name"] == "heal" for e in data)
        sink.close()

    def test_reopen_never_truncates_prior_recording(self, tmp_path):
        """A second sink on the same path (engine restart, or a scraper
        process importing the package with FLUVIO_TRACE still set) must
        never truncate the existing recording: an idle sink leaves it
        byte-identical, a writing sink rotates it aside first (its time
        base belongs to the other run — appending would overlay two
        timelines on one track)."""
        path = str(tmp_path / "t.json")
        first = TraceFileSink(path, max_bytes=1 << 20)
        first.on_span(_span(10.0, 0.005, records=1))
        first.close()
        kept = json.load(open(path))
        assert any(e.get("cat") == "batch" for e in kept)
        # a sink that never writes leaves the file byte-identical
        idle = TraceFileSink(path, max_bytes=1 << 20)
        raw_before = open(path, "rb").read()
        idle.close()
        assert open(path, "rb").read() == raw_before
        # a sink that DOES write starts its own generation; the first
        # recording survives rotated to <path>.1
        second = TraceFileSink(path, max_bytes=1 << 20)
        second.on_span(_span(20.0, 0.005, records=2))
        second.close()
        data = json.load(open(path))
        assert [e["args"]["records"] for e in data if e.get("cat") == "batch"] == [2]
        rotated = json.load(open(path + ".1"))
        assert [e["args"]["records"] for e in rotated if e.get("cat") == "batch"] == [1]

    def test_rotation_bound_respected(self, tmp_path):
        path = str(tmp_path / "t.json")
        bound = 4096
        sink = TraceFileSink(path, max_bytes=bound)
        for i in range(200):
            sink.on_span(_span(10.0 + i * 0.01, 0.005))
        sink.flush()
        # one coalesced write may overshoot before rotation triggers;
        # the bound holds within a batch's worth of slack
        assert os.path.getsize(path) <= bound + 4096
        assert os.path.exists(path + ".1")
        json.load(open(path))
        json.load(open(path + ".1"))
        sink.close()

    def test_env_install_streams_completed_spans(self, tmp_path, monkeypatch):
        path = str(tmp_path / "env.json")
        monkeypatch.setenv("FLUVIO_TRACE", path)
        sink = trace_mod.install_env_sink()
        assert sink is not None and TELEMETRY.trace_sink is sink
        TELEMETRY.end_batch(TELEMETRY.begin_batch(), records=3)
        TELEMETRY.add_heal()
        sink.flush()
        data = json.load(open(path))
        assert any(e.get("cat") == "batch" for e in data)
        assert any(e.get("name") == "heal" for e in data)
        sink.close()

    def test_env_install_noop_without_var(self, monkeypatch):
        monkeypatch.delenv("FLUVIO_TRACE", raising=False)
        assert trace_mod.install_env_sink() is None

    def test_failed_append_rolls_back_to_valid_json(self, tmp_path):
        path = str(tmp_path / "t.json")
        sink = TraceFileSink(path, max_bytes=1 << 20)
        sink.on_span(_span(10.0, 0.005))
        sink.flush()
        before = json.load(open(path))
        # one torn write (disk blip): the file must roll back to its
        # pre-append bracket, not leave a half-chunk for later appends
        real_write = sink._f.write
        calls = {"n": 0}

        def torn_write(data):
            if calls["n"] == 0:
                calls["n"] += 1
                real_write(data[: len(data) // 2])
                raise OSError("disk blip")
            return real_write(data)

        sink._f.write = torn_write
        sink.on_span(_span(20.0, 0.005))
        sink.flush()
        assert json.load(open(path)) == before  # rolled back, valid
        # the disk recovers: later appends keep working on a valid file
        sink.on_span(_span(30.0, 0.005))
        sink.flush()
        data = json.load(open(path))
        assert len([e for e in data if e.get("cat") == "batch"]) == 2
        sink.close()


# ---------------------------------------------------------------------------
# compile telemetry
# ---------------------------------------------------------------------------


class TestCompileTelemetry:
    def test_fresh_shape_bucket_records_compile_event(self):
        chain = _chain(
            ("regex-filter", {"regex": "fluvio"}),
            ("json-map", {"field": "name"}),
        )
        ex = chain.tpu_chain
        ex.process_buffer(_buf(64))
        snap = TELEMETRY.snapshot()
        assert snap["compile"]["by_kind"].get("ragged", 0) >= 1
        assert snap["compile"]["latency"]["count"] >= 1
        compiles = [
            e for e in TELEMETRY.events_json() if e["kind"] == "compile"
        ]
        assert compiles and "ragged" in compiles[0]["detail"]
        assert "w=" in compiles[0]["detail"]  # shape bucket rides along
        # warm re-run: trace-cache hits move, the compile count does not
        before = snap["compile"]["by_kind"]["ragged"]
        hits0 = snap["compile"]["jit_cache_hits"]
        ex.process_buffer(_buf(64))
        snap2 = TELEMETRY.snapshot()
        assert snap2["compile"]["by_kind"]["ragged"] == before
        assert snap2["compile"]["jit_cache_hits"] > hits0

    def test_dfa_table_build_records_compile_event(self):
        from fluvio_tpu.ops.regex_dfa import compile_regex_cached

        compile_regex_cached.cache_clear()
        compile_regex_cached("flu(vio|x)+")
        snap = TELEMETRY.snapshot()
        assert snap["compile"]["by_kind"].get("dfa_table") == 1
        compile_regex_cached("flu(vio|x)+")  # lru hit: no new event
        assert (
            TELEMETRY.snapshot()["compile"]["by_kind"]["dfa_table"] == 1
        )

    def test_recompile_storm_counts_decline(self, monkeypatch):
        from fluvio_tpu.telemetry import registry

        monkeypatch.setattr(registry, "COMPILE_STORM_N", 2)
        for i in range(4):
            TELEMETRY.add_compile("ragged", f"sig{i}", 0.01)
        snap = TELEMETRY.snapshot()
        assert snap["counters"]["declines"].get("recompile-storm", 0) == 2
        kinds = [e["kind"] for e in TELEMETRY.events_json()]
        assert "recompile-storm" in kinds

    def test_disabled_telemetry_keeps_seams_silent(self):
        TELEMETRY.enabled = False
        chain = _chain(("regex-filter", {"regex": "fluvio"}))
        chain.tpu_chain.process_buffer(_buf(32))
        snap = TELEMETRY.snapshot()
        assert snap["compile"]["by_kind"] == {}
        assert snap["gauges"] == {}
        assert snap["events_total"] == 0

    def test_prometheus_renders_compile_series(self):
        TELEMETRY.add_compile("ragged", "sig", 0.25, False)
        TELEMETRY.add_compile("striped", "sig2", 0.5, True)
        text = render_prometheus()
        assert 'fluvio_tpu_compiles_total{kind="ragged"} 1' in text
        assert 'fluvio_tpu_compiles_total{kind="striped"} 1' in text
        assert "fluvio_tpu_compile_latency_seconds_count 2" in text
        assert "fluvio_tpu_persistent_cache_hits_total 1" in text
        assert "fluvio_tpu_persistent_cache_misses_total 1" in text


# ---------------------------------------------------------------------------
# gauges
# ---------------------------------------------------------------------------


class TestGauges:
    def test_dispatch_finish_up_down(self):
        chain = _chain(
            ("regex-filter", {"regex": "fluvio"}),
            ("json-map", {"field": "name"}),
        )
        ex = chain.tpu_chain
        buf = _buf(64)
        handle = ex.dispatch_buffer(buf)
        assert TELEMETRY.gauge_value("live_batch_handles") == 1
        staged = TELEMETRY.gauge_value("hbm_staged_bytes")
        assert staged > 0
        ex.finish_buffer(buf, handle)
        assert TELEMETRY.gauge_value("live_batch_handles") == 0
        assert TELEMETRY.gauge_value("hbm_staged_bytes") == 0

    def test_discard_releases(self):
        chain = _chain(("regex-filter", {"regex": "fluvio"}))
        ex = chain.tpu_chain
        handle = ex.dispatch_buffer(_buf(32))
        assert TELEMETRY.gauge_value("live_batch_handles") == 1
        ex.discard_dispatch(handle)
        assert TELEMETRY.gauge_value("live_batch_handles") == 0
        assert TELEMETRY.gauge_value("hbm_staged_bytes") == 0

    def test_pipelined_stream_peaks_then_drains(self):
        chain = _chain(("regex-filter", {"regex": "fluvio"}))
        ex = chain.tpu_chain
        buf = _buf(32)
        peaks = []
        for out in ex.process_stream(iter([buf] * 4)):
            peaks.append(TELEMETRY.gauge_value("live_batch_handles"))
        # the two-phase loop keeps one batch in flight while yielding
        assert max(peaks) >= 1
        assert TELEMETRY.gauge_value("live_batch_handles") == 0
        assert TELEMETRY.gauge_value("hbm_staged_bytes") == 0

    @pytest.mark.skipif(
        len(jax.devices()) < 8, reason="needs 8 virtual devices"
    )
    def test_sharded_dispatch_finish_up_down(self):
        chain = _chain(("regex-filter", {"regex": "fluvio"}))
        ex = chain.tpu_chain
        ex.enable_sharded(8)
        buf = _buf(64)
        handle = ex.dispatch_buffer(buf)
        assert TELEMETRY.gauge_value("live_batch_handles") == 1
        assert TELEMETRY.gauge_value("hbm_staged_bytes") > 0
        ex.finish_buffer(buf, handle)
        assert TELEMETRY.gauge_value("live_batch_handles") == 0
        assert TELEMETRY.gauge_value("hbm_staged_bytes") == 0
        assert TELEMETRY.snapshot()["compile"]["by_kind"].get("sharded", 0) >= 1

    def test_deadletter_occupancy_gauge(self, tmp_path):
        from fluvio_tpu.resilience.deadletter import quarantine_batch
        from fluvio_tpu.smartmodule.types import SmartModuleInput

        inp = SmartModuleInput.from_records([Record(value=b"poison")])
        d = str(tmp_path / "dl")
        for i in range(3):
            quarantine_batch(
                [{"name": "f"}], inp, ValueError("a"), ValueError("b"),
                directory=d,
            )
        assert TELEMETRY.gauge_value("deadletter_entries") == 3
        # eviction keeps the gauge at the bound, not the write count
        for i in range(4):
            quarantine_batch(
                [{"name": "f"}], inp, ValueError("a"), ValueError("b"),
                directory=d, max_entries=2,
            )
        assert TELEMETRY.gauge_value("deadletter_entries") == 2

    def test_queue_depth_release_is_idempotent(self):
        from fluvio_tpu.spu.smart_chain import PendingSlice

        ps = PendingSlice(
            batches=[], chunks=[], planned_next=0, total_raw=0,
            base0=0, ts0=0, count=0,
        )
        TELEMETRY.gauge_add("inflight_queue_depth", 2)
        ps.tracked_depth = 2
        ps.release_depth()
        ps.release_depth()  # double release must not go negative
        assert TELEMETRY.gauge_value("inflight_queue_depth") == 0

    def test_disabled_telemetry_zero_cost_gauges(self):
        TELEMETRY.enabled = False
        TELEMETRY.gauge_add("hbm_staged_bytes", 100)
        TELEMETRY.gauge_set("deadletter_entries", 5)
        TELEMETRY.enabled = True
        assert TELEMETRY.snapshot()["gauges"] == {}


# ---------------------------------------------------------------------------
# span-ring dropped count
# ---------------------------------------------------------------------------


class TestSpanRingDropped:
    def test_dropped_through_snapshot_and_prometheus(self):
        t = PipelineTelemetry(ring_capacity=4)
        for i in range(7):
            t.end_batch(t.begin_batch(), records=1)
        assert t.spans.dropped == 3
        snap = t.snapshot()
        assert snap["spans_dropped"] == 3
        assert snap["spans_retained"] == 4 and snap["spans_total"] == 7
        text = render_prometheus(telemetry=t)
        assert "fluvio_tpu_spans_dropped_total 3" in text

    def test_unwrapped_ring_reports_zero(self):
        ring = SpanRing(8)
        for i in range(5):
            ring.push(_span(1.0 + i, 0.1))
        assert ring.dropped == 0 and ring.total == 5


# ---------------------------------------------------------------------------
# export surfaces: monitoring socket + CLI
# ---------------------------------------------------------------------------


class _Ctx:
    def __init__(self):
        from fluvio_tpu.spu.metrics import SpuMetrics

        self.metrics = SpuMetrics()


def _with_server(tmp_path, fn):
    from fluvio_tpu.spu.monitoring import MonitoringServer

    async def run():
        server = MonitoringServer(_Ctx(), str(tmp_path / "m.sock"))
        await server.start()
        try:
            return await fn(server)
        finally:
            await server.stop()

    return asyncio.run(run())


class TestTraceExportSurfaces:
    def _populate(self):
        span = TELEMETRY.begin_batch()
        span.add("stage", 0.001)
        TELEMETRY.end_batch(span, records=16)
        TELEMETRY.add_compile("ragged", "sig w=64", 0.3, True)

    def test_monitoring_socket_trace_mode(self, tmp_path):
        from fluvio_tpu.spu.monitoring import read_trace

        self._populate()
        doc = _with_server(tmp_path, lambda s: read_trace(s.path))
        assert doc["displayTimeUnit"] == "ms"
        names = {e.get("name") for e in doc["traceEvents"]}
        assert "compile" in names
        assert any(
            e.get("cat") == "batch" for e in doc["traceEvents"]
        )

    def test_cli_trace_writes_perfetto_file(self, tmp_path):
        import argparse

        from fluvio_tpu.cli.trace import trace as trace_cmd

        self._populate()
        out_path = str(tmp_path / "out.json")

        def run(server):
            args = argparse.Namespace(out=out_path, path=server.path)
            return trace_cmd(args)

        rc = _with_server(tmp_path, run)
        assert rc == 0
        doc = json.load(open(out_path))
        assert any(e.get("cat") == "batch" for e in doc["traceEvents"])

    def test_metrics_table_renders_compile_and_gauges(self):
        from fluvio_tpu.cli.metrics import render_metrics_table

        self._populate()
        TELEMETRY.gauge_add("live_batch_handles", 1)
        table = render_metrics_table({"telemetry": TELEMETRY.snapshot()})
        assert "jit compiles" in table and "ragged" in table
        assert "gauges" in table and "live_batch_handles" in table
