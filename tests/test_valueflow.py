"""Value-flow analyzer (FLV3xx): repo gate + injected hazards + the
scale-probe differential.

Three halves, mirroring `tests/test_concurrency.py`:

1. **Repo gate** — `analyze --values` must run clean over the
   registered engine modules; every suppression must be a documented
   relaxation.
2. **Injected-hazard pins** — each rule (FLV301 store/binop, FLV302,
   FLV303 with the np-widens/jnp-does-not asymmetry, FLV304) must
   catch its class on synthetic sources, and the SHARED noqa grammar
   must suppress (including a combined multi-analyzer comment).
3. **Scale-probe differential** — for every suppressed FLV301/303
   site, the analyzer's witness shape (the smallest in-bounds shape
   that overflows) must be refused by a runtime guard with a typed
   error: `FlatAddressingError` for flat/matrix extents, the
   `SLICE_STRIDE`/`MAX_COALESCE` guards in `coalesce_buffers`. The
   static prediction and the runtime refusal pin each other, the same
   pattern as PR 6's preflight-vs-telemetry and PR 7's lockwatch.
"""

from __future__ import annotations

import time

import numpy as np
import pytest

from fluvio_tpu.analysis.valueflow import (
    BOUNDS,
    MAX_RECORD_WIDTH,
    RULES,
    VALUEFLOW_MODULES,
    analyze_values_package,
    analyze_values_sources,
)

I32_MAX = 2**31 - 1


def _codes(report):
    return [f.code for f in report.findings]


# ---------------------------------------------------------------------------
# The repo gate
# ---------------------------------------------------------------------------


def test_package_valueflow_is_clean():
    """ISSUE-14 acceptance: zero unsuppressed FLV3xx findings across
    the kernel/executor/admission/partition arithmetic modules."""
    report = analyze_values_package()
    assert report.files >= 10, "module scope silently shrank"
    assert not report.findings, "\n".join(str(f) for f in report.findings)


def test_every_suppression_sits_on_a_noqa_line():
    """A suppressed finding must map to an actual `# noqa: FLV3xx`
    comment (the audit surface stays greppable)."""
    report = analyze_values_package()
    assert report.suppressed, "the documented relaxations disappeared"
    for f in report.suppressed:
        with open(f.path, "r", encoding="utf-8") as fh:
            line = fh.read().splitlines()[f.line - 1]
        assert "noqa" in line and f.code[:6] in line, (f.path, f.line)


def test_rules_are_all_error_severity():
    # the gate's rc-1 contract: a predicted overflow is a deploy
    # blocker, exactly like a predicted interpreter spill
    assert all(level == "error" for level, _ in RULES.values())


def test_analyzer_runtime_is_bounded():
    """CI-tooling satellite: the whole-repo value-flow scan (plus the
    env lint) stays under the 30 s self-runtime bound — the same smoke
    gate pattern as the pallas compile-size gate."""
    from fluvio_tpu.analysis.envreg import lint_env_package

    t0 = time.monotonic()
    analyze_values_package()
    lint_env_package()
    elapsed = time.monotonic() - t0
    assert elapsed < 30.0, f"analyzer self-runtime {elapsed:.1f}s"


# ---------------------------------------------------------------------------
# Injected hazards (one per rule, mirroring test_concurrency's pins)
# ---------------------------------------------------------------------------


def test_store_into_i32_slot_flags_flv301():
    src = (
        "import numpy as np\n"
        "def f(rows, width):\n"
        "    out = np.zeros(rows, dtype=np.int32)\n"
        "    out[0] = rows * width\n"
        "    return out\n"
    )
    report = analyze_values_sources({"m.py": src})
    assert _codes(report) == ["FLV301"]
    assert report.findings[0].line == 4


def test_i32_array_arithmetic_flags_flv301():
    # the coalesce-base class: i32 offset-delta column + a base that
    # can reach past int32 at the declared slice-stride bounds
    src = (
        "def f(offset_deltas):\n"
        "    return offset_deltas + (1 << 21) * 2047\n"
    )
    report = analyze_values_sources({"m.py": src})
    assert _codes(report) == ["FLV301"]


def test_safe_arithmetic_is_clean():
    src = (
        "import numpy as np\n"
        "def f(rows):\n"
        "    out = np.zeros(rows, dtype=np.int64)\n"
        "    out[0] = rows * 8\n"
        "    return out\n"
    )
    assert not analyze_values_sources({"m.py": src}).findings


def test_narrowing_cast_flags_flv302():
    src = (
        "import numpy as np\n"
        "def f(lengths):\n"
        "    starts = np.cumsum(lengths.astype(np.int64))\n"
        "    return starts.astype(np.int32)\n"
    )
    report = analyze_values_sources({"m.py": src})
    assert _codes(report) == ["FLV302"]
    assert report.findings[0].line == 4


def test_device_cumsum_flags_flv303_host_twin_is_clean():
    """THE asymmetry the rule encodes: an identical formula is safe on
    the host (np widens int32 accumulation to int64) and overflows on
    the chip (jnp keeps int32)."""
    device = (
        "import jax.numpy as jnp\n"
        "def f(lengths):\n"
        "    return jnp.cumsum(lengths)\n"
    )
    host = (
        "import numpy as np\n"
        "def f(lengths):\n"
        "    return np.cumsum(lengths)\n"
    )
    dev_report = analyze_values_sources({"m.py": device})
    assert _codes(dev_report) == ["FLV303"]
    assert not analyze_values_sources({"m.py": host}).findings


def test_explicit_wide_accumulator_is_clean():
    src = (
        "import jax.numpy as jnp\n"
        "def f(lengths):\n"
        "    return jnp.cumsum(lengths, dtype=jnp.int64)\n"
    )
    assert not analyze_values_sources({"m.py": src}).findings


def test_pyint_wraparound_narrowing_flags_flv304():
    src = (
        "import numpy as np\n"
        "def mix(rows):\n"
        "    h = rows * 0x9E3779B97F4A7C15\n"
        "    return np.int64(h)\n"
    )
    report = analyze_values_sources({"m.py": src})
    assert _codes(report) == ["FLV304"]


def test_noqa_suppresses_and_stays_enumerable():
    src = (
        "import jax.numpy as jnp\n"
        "def f(lengths):\n"
        "    return jnp.cumsum(lengths)  # noqa: FLV303\n"
    )
    report = analyze_values_sources({"m.py": src})
    assert not report.findings
    assert [f.code for f in report.suppressed] == ["FLV303"]


def test_combined_multi_analyzer_noqa_satisfies_valueflow():
    """Shared-parser satellite: ONE comment listing codes from several
    analyzers (`noqa: FLV201,FLV303`) suppresses each analyzer's own
    code — the three per-linter parsers are one helper now."""
    src = (
        "import jax.numpy as jnp\n"
        "def f(lengths):\n"
        "    return jnp.cumsum(lengths)  # noqa: FLV201,FLV303\n"
    )
    report = analyze_values_sources({"m.py": src})
    assert not report.findings
    # and the concurrency analyzer accepts the same comment shape for
    # ITS code on a line it would otherwise flag
    from fluvio_tpu.analysis.concurrency import analyze_sources

    threaded = (
        "import threading\n"
        "_lock = threading.Lock()\n"
        "_cache = {}\n"
        "def worker():\n"
        "    with _lock:\n"
        "        _cache['a'] = 1\n"
        "    refresh()\n"
        "def refresh():\n"
        "    _cache['b'] = 2  # noqa: FLV201,FLV301\n"
        "def spawn():\n"
        "    t = threading.Thread(target=worker)\n"
        "    t.start()\n"
    )
    conc = analyze_sources({"mod": threaded})
    assert not [f for f in conc.findings if f.code == "FLV201"]


def test_unknown_values_stay_silent():
    """Soundness posture: no bounds, no finding — the analyzer must
    not hallucinate overflow from unseeded names."""
    src = (
        "import numpy as np\n"
        "def f(a, b):\n"
        "    out = np.zeros(8, dtype=np.int32)\n"
        "    out[0] = a * b\n"
        "    return out\n"
    )
    assert not analyze_values_sources({"m.py": src}).findings


# ---------------------------------------------------------------------------
# Scale-probe differential: witness shapes vs runtime guards
# ---------------------------------------------------------------------------


def test_witness_shape_is_minimal_and_overflowing():
    src = (
        "import jax.numpy as jnp\n"
        "def f(lengths):\n"
        "    return jnp.cumsum(lengths)\n"
    )
    f = analyze_values_sources({"m.py": src}).findings[0]
    w = f.detail["witness"]
    assert w["count"] * w["elem"] > I32_MAX
    assert (w["count"] - 1) * w["elem"] <= I32_MAX


def test_flat_addressing_guard_refuses_the_witness_shape():
    """The FLV303 noqas in executor/stripes cite
    `buffer.check_flat_addressing`: at the analyzer's witness shape
    (smallest in-bounds batch whose aligned flat passes int32) the
    guard must raise its typed error — and admit one step below."""
    from fluvio_tpu.smartengine.tpu.buffer import (
        FlatAddressingError,
        check_flat_addressing,
    )

    elem = MAX_RECORD_WIDTH  # already 4-aligned
    count = I32_MAX // elem + 1  # 2048 rows of 1 MiB
    assert count <= BOUNDS["ROWS"], "witness must stay inside bounds"
    lengths = np.full(count, elem, dtype=np.int64)
    with pytest.raises(FlatAddressingError):
        check_flat_addressing(lengths)
    assert check_flat_addressing(lengths[:-1]) <= I32_MAX


def test_matrix_guard_refuses_oversized_from_arrays():
    """The FLV303 noqa in `_packed_payload` cites the staging matrix
    bound: a rows x width extent past int32 must be refused at
    adoption (broadcast view: no 4 GiB allocation happens here)."""
    from fluvio_tpu.smartengine.tpu.buffer import (
        FlatAddressingError,
        RecordBuffer,
    )

    rows, width = 1 << 16, 1 << 16  # 2**32 > I32_MAX
    values = np.broadcast_to(np.zeros((1, 1), dtype=np.uint8), (rows, width))
    with pytest.raises(FlatAddressingError):
        RecordBuffer.from_arrays(values, np.zeros(rows, dtype=np.int32))


def test_coalesce_delta_guard_refuses_stride_aliasing():
    """The FLV301 noqa in `coalesce_buffers` cites two guards; this is
    the new one: a source slice whose offset deltas reach SLICE_STRIDE
    would alias another slice's base band (and overflow i32 at the
    2047-slice bound) — typed refusal, dispatch solo instead."""
    from fluvio_tpu.admission.batcher import SLICE_STRIDE, coalesce_buffers
    from fluvio_tpu.smartengine.tpu.buffer import RecordBuffer

    ok = RecordBuffer.from_arrays(
        np.zeros((8, 32), dtype=np.uint8),
        np.full(8, 4, dtype=np.int32),
        count=2,
    )
    bad = RecordBuffer.from_arrays(
        np.zeros((8, 32), dtype=np.uint8),
        np.full(8, 4, dtype=np.int32),
        count=2,
        offset_deltas=np.full(8, SLICE_STRIDE, dtype=np.int32),
    )
    merged, bases = coalesce_buffers([ok, ok])
    assert merged.count == 4 and bases == [0, SLICE_STRIDE]
    with pytest.raises(ValueError, match="stride"):
        coalesce_buffers([ok, bad])


def test_batcher_routes_stride_reaching_slice_solo():
    """The guard must protect WITHOUT collateral damage: a slice whose
    deltas reach the stride flushes solo from `add()` — through the
    same warmed-cover/accounting `_flush` machinery as every other
    flush, with its deltas intact — and the slices already accumulated
    in its bucket keep coalescing. The `coalesce_buffers` raise is the
    shared-merge backstop, never the admission path's behavior."""
    from fluvio_tpu.admission.batcher import (
        SLICE_STRIDE,
        ShapeBucketBatcher,
        split_output,
    )
    from fluvio_tpu.smartengine.tpu.buffer import RecordBuffer

    flushes = []
    batcher = ShapeBucketBatcher(
        dispatch=flushes.append, row_target=6, deadline_s=60.0,
    )
    ok = RecordBuffer.from_arrays(
        np.zeros((8, 32), dtype=np.uint8),
        np.full(8, 4, dtype=np.int32),
        count=2,
    )
    wide_deltas = RecordBuffer.from_arrays(
        np.zeros((8, 32), dtype=np.uint8),
        np.full(8, 4, dtype=np.int32),
        count=2,
        offset_deltas=np.full(8, SLICE_STRIDE, dtype=np.int32),
    )
    batcher.add("c", ok)
    solo = batcher.add("c", wide_deltas)
    assert [f.cause for f in solo] == ["solo"]
    assert solo[0].bases == [0] and solo[0].items == [wide_deltas]
    assert solo[0].buffer.count == 2
    # the single-source route-back keeps EVERY big-delta survivor
    routed = split_output(solo[0].buffer, solo[0].bases)
    assert len(routed) == 1 and len(routed[0]) == 2
    assert all(delta >= SLICE_STRIDE for _, delta in routed[0])
    # the pending bucket survived and still coalesces to full
    full = batcher.add("c", ok) + batcher.add("c", ok)
    merged = [f for f in full if f.cause == "batch-full"]
    assert merged and merged[0].buffer.count == 6


def test_valueflow_bounds_track_buffer_constants():
    from fluvio_tpu.smartengine.tpu import buffer

    assert BOUNDS["MAX_RECORD_WIDTH"] == buffer.MAX_RECORD_WIDTH
    assert BOUNDS["MAX_WIDTH"] == buffer.MAX_WIDTH
    assert BOUNDS["MIN_ROWS"] == buffer.MIN_ROWS
    assert BOUNDS["MIN_WIDTH"] == buffer.MIN_WIDTH


def test_coalesce_count_guard_still_refuses_past_max():
    """The pre-existing MAX_COALESCE guard (the PR-10 human catch that
    motivated this analyzer) stays pinned: base arithmetic past 2047
    source slices must refuse, not wrap."""
    from fluvio_tpu.admission.batcher import MAX_COALESCE, coalesce_buffers
    from fluvio_tpu.smartengine.tpu.buffer import RecordBuffer

    one = RecordBuffer.from_arrays(
        np.zeros((8, 32), dtype=np.uint8),
        np.full(8, 4, dtype=np.int32),
        count=1,
    )
    with pytest.raises(ValueError, match="int32"):
        coalesce_buffers([one] * (MAX_COALESCE + 1))


def test_ragged_values_guard_covers_the_narrowing_cast():
    """The FLV302 noqa in `ragged_values` cites the guard one line
    above it: same call, same lengths — the cast can only run on
    guard-admitted totals. Pin that the guard actually runs there."""
    from fluvio_tpu.smartengine.tpu import buffer as buffer_mod

    buf = buffer_mod.RecordBuffer.from_arrays(
        np.zeros((8, 32), dtype=np.uint8),
        np.full(8, 4, dtype=np.int32),
        count=2,
    )
    calls = []
    orig = buffer_mod.check_flat_addressing

    def spy(lengths, count=None):
        calls.append(len(lengths))
        return orig(lengths, count)

    buffer_mod.check_flat_addressing = spy
    try:
        buf.ragged_values()
    finally:
        buffer_mod.check_flat_addressing = orig
    assert calls, "ragged_values no longer guards flat addressing"


def test_module_scope_names_exist():
    """VALUEFLOW_MODULES must keep pointing at real files (a rename
    must not silently shrink the gate's scope)."""
    import os

    import fluvio_tpu

    root = os.path.dirname(os.path.abspath(fluvio_tpu.__file__))
    missing = [
        rel for rel in VALUEFLOW_MODULES
        if not os.path.exists(os.path.join(root, rel))
    ]
    assert not missing, missing
