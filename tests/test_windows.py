"""Windowed-state engine exactness: device carry vs the host oracle.

Every test pins the SAME two surfaces the bench pins: the bank snapshot
(the device carry, bit-for-bit) after every batch, and the broker-side
`MaterializedView.table()` folded from the delta stream against
`HostWindowReference.table()`. The chaos matrix re-runs those pins with
faults armed at each engine seam; the failover tests ride the
CarryReplica ladder and pin exactly-once delta serving.
"""

import numpy as np
import pytest

from fluvio_tpu.partition.failover import CarryReplica
from fluvio_tpu.resilience import faults
from fluvio_tpu.windows import (
    HostWindowReference,
    MaterializedView,
    PartitionedWindowRuntime,
    WindowCapacityError,
    WindowJits,
    WindowSpec,
    WindowedRuntime,
    merge_banks,
)

# tiny geometries keep every shape in the smallest jit buckets
FOREVER = 10**9  # lateness that never closes a window

# specs are hashable by design so every distinct geometry compiles its
# kernels exactly ONCE across the whole module — the same shared-jits
# discipline PartitionedWindowRuntime uses per broker
_JITS = {}


def _spec(window_ms=100, slide_ms=0, op="add", keyed=False, lateness_ms=0,
          capacity=512, emit_capacity=256, delta_only=True):
    """Fully pinned spec: no env-resolved capacities, so tests stay
    hermetic under any FLUVIO_WINDOW* ambient config."""
    return WindowSpec(
        window_ms=window_ms, slide_ms=slide_ms, op=op, keyed=keyed,
        lateness_ms=lateness_ms, capacity=capacity,
        emit_capacity=emit_capacity, delta_only=delta_only,
    )


def _jits(spec):
    jits = _JITS.get(spec)
    if jits is None:
        jits = _JITS[spec] = WindowJits(spec)
    return jits


def _runtime(spec):
    return WindowedRuntime(spec, jits=_jits(spec))


def _partitioned(spec, replica=None):
    return PartitionedWindowRuntime(spec, replica=replica, jits=_jits(spec))


def _cols(batch):
    keys = np.array([k for k, _, _ in batch], dtype=np.int64)
    contribs = np.array([c for _, c, _ in batch], dtype=np.int64)
    ts = np.array([t for _, _, t in batch], dtype=np.int64)
    return contribs, keys, ts


def _drive(rt, view, ref, batches):
    """Feed (key, contrib, ts) batches through engine + oracle, pinning
    the carry and the per-batch header counts after every batch."""
    for batch in batches:
        delta = rt.ingest_arrays(*_cols(batch))
        view.apply_delta(delta)
        pins = ref.process_batch(batch)
        assert delta.n_closed == pins["closed"]
        assert delta.n_late == pins["late"]
        assert delta.n_invalid == pins["invalid"]
        assert delta.watermark == pins["watermark"]
        assert rt.bank.snapshot() == ref.bank_entries()


def _gen_batches(rng, n_batches, per, n_keys, step, regress=0):
    """Mostly-monotonic event time with optional backwards jitter (the
    late-record source); contribs include negatives so sum-vs-max bugs
    can't cancel out."""
    t = 0
    batches = []
    for _ in range(n_batches):
        batch = []
        for _ in range(per):
            t += int(rng.integers(0, step))
            ts = max(t - int(rng.integers(0, regress + 1)), 0)
            batch.append(
                (int(rng.integers(0, n_keys)),
                 int(rng.integers(-50, 100)), ts)
            )
        batches.append(batch)
    return batches


def _pack(values, ts):
    """Raw records -> RecordBuffer (the process_buffer seam); absolute
    event time rides timestamp_deltas with base_timestamp unset."""
    from fluvio_tpu.smartengine.tpu.buffer import RecordBuffer, bucket_width

    n = len(values)
    width = bucket_width(max(len(v) for v in values))
    rows = 8
    while rows < n:
        rows *= 2
    arr = np.zeros((rows, width), dtype=np.uint8)
    lengths = np.zeros(rows, dtype=np.int32)
    for i, v in enumerate(values):
        arr[i, : len(v)] = np.frombuffer(v, dtype=np.uint8)
        lengths[i] = len(v)
    tcol = np.zeros(rows, dtype=np.int64)
    tcol[:n] = np.asarray(ts, dtype=np.int64)
    return RecordBuffer.from_arrays(
        arr, lengths, count=n, timestamp_deltas=tcol
    )


@pytest.fixture(autouse=True)
def _clean_faults():
    from fluvio_tpu.telemetry import memory as memory_mod

    faults.FAULTS.clear()
    memory_mod.reset_engine()
    yield
    faults.FAULTS.clear()
    # ISSUE-20 standing invariant: whatever each test did — faults,
    # failover, capacity errors — the transient device-memory owners
    # (emit fetch buffers above all) must have drained at quiesce
    eng = memory_mod.peek()
    if eng is not None:
        eng.assert_drained()
    memory_mod.reset_engine()


class TestExactness:
    def test_tumbling_multi_batch(self):
        spec = _spec()
        rng = np.random.default_rng(7)
        rt, view, ref = (
            _runtime(spec), MaterializedView(spec),
            HostWindowReference(spec),
        )
        _drive(rt, view, ref, _gen_batches(rng, 5, 40, 1, step=12))
        assert view.table() == ref.table()
        assert view.close_events == len(ref.closed)
        assert view.duplicate_closes == 0

    def test_sliding_multi_batch(self):
        spec = _spec(window_ms=100, slide_ms=25)
        rng = np.random.default_rng(11)
        rt, view, ref = (
            _runtime(spec), MaterializedView(spec),
            HostWindowReference(spec),
        )
        _drive(rt, view, ref, _gen_batches(rng, 4, 32, 1, step=10))
        assert view.table() == ref.table()

    def test_keyed_multi_batch(self):
        spec = _spec(keyed=True)
        rng = np.random.default_rng(13)
        rt, view, ref = (
            _runtime(spec), MaterializedView(spec),
            HostWindowReference(spec),
        )
        _drive(rt, view, ref, _gen_batches(rng, 4, 48, 8, step=6))
        assert view.table() == ref.table()

    @pytest.mark.parametrize("op", ["max", "min"])
    def test_minmax_monoids(self, op):
        spec = _spec(op=op)
        rng = np.random.default_rng(17)
        rt, view, ref = (
            _runtime(spec), MaterializedView(spec),
            HostWindowReference(spec),
        )
        _drive(rt, view, ref, _gen_batches(rng, 3, 24, 1, step=15))
        assert view.table() == ref.table()

    def test_late_records_drop_not_fold(self):
        # batch 2 carries records behind the watermark: the closed
        # window's total must NOT change, and both sides count the drop
        spec = _spec(lateness_ms=0)
        rt, view, ref = (
            _runtime(spec), MaterializedView(spec),
            HostWindowReference(spec),
        )
        b0 = [(0, 5, 10), (0, 7, 40)]
        b1 = [(0, 1, 250)]  # wm 250 closes [0, 100)
        b2 = [(0, 100, 20), (0, 3, 260)]  # ts=20 is late now
        _drive(rt, view, ref, [b0, b1, b2])
        assert ref.late == 1
        assert view.table()[(0, 0)] == (12, 2, "closed")
        assert view.table() == ref.table()

    def test_buffer_parse_path_unkeyed(self):
        # the RecordBuffer value-parse entry (what the bench drives)
        spec = _spec()
        rng = np.random.default_rng(19)
        rt, view, ref = (
            _runtime(spec), MaterializedView(spec),
            HostWindowReference(spec),
        )
        t = 0
        for _ in range(3):
            vals = [int(rng.integers(0, 1000)) for _ in range(24)]
            ts = [(t := t + int(rng.integers(0, 9))) for _ in vals]
            delta = rt.process_buffer(
                _pack([str(v).encode() for v in vals], ts)
            )
            view.apply_delta(delta)
            ref.process_batch([(0, v, s) for v, s in zip(vals, ts)])
            assert rt.bank.snapshot() == ref.bank_entries()
        assert view.table() == ref.table()

    def test_buffer_parse_path_keyed(self):
        # "<key> <value>" records through the fused two-int parse
        spec = _spec(keyed=True)
        rng = np.random.default_rng(23)
        rt, view, ref = (
            _runtime(spec), MaterializedView(spec),
            HostWindowReference(spec),
        )
        t = 0
        for _ in range(3):
            recs = [
                (int(rng.integers(0, 8)), int(rng.integers(0, 1000)))
                for _ in range(24)
            ]
            ts = [(t := t + int(rng.integers(0, 9))) for _ in recs]
            delta = rt.process_buffer(
                _pack([f"{k} {v}".encode() for k, v in recs], ts)
            )
            view.apply_delta(delta)
            ref.process_batch(
                [(k, v, s) for (k, v), s in zip(recs, ts)]
            )
            assert rt.bank.snapshot() == ref.bank_entries()
        assert view.table() == ref.table()

    def test_out_of_range_keys_drop_and_count(self):
        # composite-id packing holds for keys in [0, 2^31) only: an
        # out-of-range key would alias into another key's window-id
        # space, so the kernel drops such rows (no fold, no watermark
        # advance) and counts them; the host reference mirrors the rule
        from fluvio_tpu.windows.spec import KEY_STRIDE

        spec = _spec(keyed=True)
        rt, view, ref = (
            _runtime(spec), MaterializedView(spec),
            HostWindowReference(spec),
        )
        batch = [
            (1, 5, 10),
            (KEY_STRIDE, 7, 20),       # aliases key 0 if folded
            (-3, 9, 30),               # negative composite id
            (KEY_STRIDE * 4, 11, 40),  # would overflow into key 4
            (2, 6, 50),
            (1 << 40, 13, 999),        # max-ts row is invalid: wm stays 50
        ]
        delta = rt.ingest_arrays(*_cols(batch))
        view.apply_delta(delta)
        pins = ref.process_batch(batch)
        assert delta.n_invalid == 4 == pins["invalid"]
        assert delta.watermark == 50 == pins["watermark"]
        assert rt.bank.snapshot() == ref.bank_entries()
        assert view.table() == ref.table()
        assert {k for (k, _s) in view.table()} == {1, 2}

    def test_delta_smaller_than_full_state(self):
        spec = _spec()
        rt = _runtime(spec)
        batch = [(0, i, i * 3) for i in range(64)]
        delta = rt.ingest_arrays(*_cols(batch))
        assert delta.kind == "rows"
        assert delta.delta_bytes < delta.full_bytes
        assert delta.row_count() >= delta.n_closed


class TestSlidingOverlapFuzz:
    @pytest.mark.parametrize(
        "window_ms,slide_ms,seed",
        [(120, 40, 101), (100, 20, 202), (90, 30, 303)],
    )
    def test_fuzz_vs_host_reference(self, window_ms, slide_ms, seed):
        # random keys, jittered event time WITH regressions: every
        # record fans out to window_ms/slide_ms overlapping windows and
        # some arrive late — the full assignment/close/late matrix
        spec = _spec(window_ms=window_ms, slide_ms=slide_ms, keyed=True,
                     lateness_ms=slide_ms)
        rng = np.random.default_rng(seed)
        rt, view, ref = (
            _runtime(spec), MaterializedView(spec),
            HostWindowReference(spec),
        )
        _drive(
            rt, view, ref,
            _gen_batches(
                rng, 4, 40, 4, step=8,
                regress=window_ms + 4 * slide_ms,
            ),
        )
        assert view.table() == ref.table()
        assert ref.late > 0, "fuzz must exercise the late path"


class TestMergeBanks:
    def test_shard_merge_associative_and_serial_equal(self):
        # split ingest + merge == one-stream ingest, under both
        # association orders (the striped/sharded combine contract)
        spec = _spec(keyed=True, lateness_ms=FOREVER)
        jits = _jits(spec)
        rng = np.random.default_rng(29)
        records = [
            b for batch in _gen_batches(rng, 3, 30, 6, step=7)
            for b in batch
        ]
        parts = [records[0::3], records[1::3], records[2::3]]
        shards = []
        for part in parts:
            rt = WindowedRuntime(spec, jits=jits)
            rt.ingest_arrays(*_cols(part))
            shards.append(rt.bank)
        serial = WindowedRuntime(spec, jits=jits)
        serial.ingest_arrays(*_cols(records))
        left = merge_banks(jits, merge_banks(jits, shards[0], shards[1]),
                           shards[2])
        right = merge_banks(jits, shards[0],
                            merge_banks(jits, shards[1], shards[2]))
        assert left.snapshot() == serial.bank.snapshot()
        assert right.snapshot() == serial.bank.snapshot()


class TestChaosMatrix:
    POINTS = ("stage", "dispatch", "device", "fetch")

    @pytest.mark.parametrize("point", POINTS)
    def test_transient_fault_retries_bit_equal(self, point):
        # transient fault mid-stream: the engine retries ONCE against
        # the untouched carry, and the results stay bit-equal to an
        # un-faulted host fold
        spec = _spec()
        rng = np.random.default_rng(31)
        rt, view, ref = (
            _runtime(spec), MaterializedView(spec),
            HostWindowReference(spec),
        )
        batches = _gen_batches(rng, 3, 20, 1, step=10)
        t = 0
        for i, batch in enumerate(batches):
            if i == 1:
                faults.FAULTS.inject(point, first=1)
            vals = [str(c).encode() for _, c, _ in batch]
            ts = [s for _, _, s in batch]
            delta = rt.process_buffer(_pack(vals, ts))
            view.apply_delta(delta)
            ref.process_batch(batch)
            assert rt.bank.snapshot() == ref.bank_entries()
        assert view.table() == ref.table()

    @pytest.mark.parametrize("point", POINTS)
    def test_deterministic_fault_leaves_carry_valid(self, point):
        # a non-transient fault raises (no blind retry) BEFORE the bank
        # commits: the previous carry survives and replaying the same
        # buffer afterwards lands the exact result
        spec = _spec()
        rng = np.random.default_rng(37)
        rt, view, ref = (
            _runtime(spec), MaterializedView(spec),
            HostWindowReference(spec),
        )
        b0, b1 = _gen_batches(rng, 2, 20, 1, step=10)
        view.apply_delta(rt.process_buffer(
            _pack([str(c).encode() for _, c, _ in b0],
                  [s for _, _, s in b0])
        ))
        ref.process_batch(b0)
        before = rt.bank.snapshot()
        faults.FAULTS.inject(
            point, first=1,
            exc=faults.InjectedFault(point, transient=False),
        )
        buf = _pack([str(c).encode() for _, c, _ in b1],
                    [s for _, _, s in b1])
        with pytest.raises(faults.InjectedFault):
            rt.process_buffer(buf)
        assert rt.bank.snapshot() == before, "faulted batch must not commit"
        faults.FAULTS.clear()
        view.apply_delta(rt.process_buffer(buf))
        ref.process_batch(b1)
        assert rt.bank.snapshot() == ref.bank_entries()
        assert view.table() == ref.table()

    def test_env_grammar_arms_window_seams(self, monkeypatch):
        # the FLUVIO_FAULTS env spec drives the same seams (chaos runs
        # arm brokers without code changes)
        monkeypatch.setenv("FLUVIO_FAULTS", "device:first=1")
        faults._load_from_env()
        spec = _spec()
        rt, ref = _runtime(spec), HostWindowReference(spec)
        batch = [(0, 5, 10), (0, 7, 40)]
        rt.process_buffer(
            _pack([b"5", b"7"], [10, 40])
        )  # transient by default: retried internally
        ref.process_batch(batch)
        assert rt.bank.snapshot() == ref.bank_entries()


class TestFailoverAndMigration:
    def _batches(self):
        rng = np.random.default_rng(41)
        return _gen_batches(rng, 3, 20, 4, step=12)

    def test_seed_restores_bit_equal_bank(self):
        spec = _spec(keyed=True)
        replica = CarryReplica()
        a = _partitioned(spec, replica=replica)
        ref = HostWindowReference(spec)
        batches = self._batches()
        for batch in batches[:2]:
            vals = [f"{k} {c}".encode() for k, c, _ in batch]
            a.process_buffer("t", 0, _pack(vals, [s for _, _, s in batch]))
            ref.process_batch(batch)
        # promotion: a fresh runtime (standby broker) seeds from the
        # replica's last committed snapshot
        b = _partitioned(spec, replica=replica)
        offset = b.seed("t", 0)
        assert offset == sum(len(x) for x in batches[:2])
        assert b.snapshot("t", 0) == a.snapshot("t", 0)
        assert b.snapshot("t", 0) == ref.bank_entries()

    def test_exactly_once_served_deltas_across_failover(self):
        # the replay ladder re-serves the last batch's delta after
        # promotion; the view folds it idempotently (no double counts,
        # duplicate closes observable) and the stream continues exact
        spec = _spec()
        replica = CarryReplica()
        a = _partitioned(spec, replica=replica)
        view, ref = MaterializedView(spec), HostWindowReference(spec)
        b0 = [(0, 5, 10), (0, 7, 40)]
        b1 = [(0, 2, 250), (0, 9, 260)]  # closes [0, 100)
        b2 = [(0, 4, 470), (0, 6, 480)]
        deltas = []
        for batch in (b0, b1):
            vals = [str(c).encode() for _, c, _ in batch]
            d = a.process_buffer("t", 0,
                                 _pack(vals, [s for _, _, s in batch]))
            deltas.append(d)
            view.apply_delta(d)
            ref.process_batch(batch)
        assert deltas[1].n_closed == 1
        assert deltas[0].offset == 0 and deltas[1].offset == 2
        b = _partitioned(spec, replica=replica)
        offset = b.seed("t", 0)
        assert offset == 4
        # failover replay: batch 1's delta arrives AGAIN
        table_before = view.table()
        view.apply_delta(deltas[1])
        assert view.table() == table_before, "replay must not double-count"
        assert view.duplicate_closes == 1
        # new leader resumes from the committed offset
        d2 = b.process_buffer(
            "t", 0, _pack([b"4", b"6"], [470, 480])
        )
        assert d2.offset == offset
        view.apply_delta(d2)
        ref.process_batch(b2)
        assert b.snapshot("t", 0) == ref.bank_entries()
        assert view.table() == ref.table()

    def test_migration_mid_window_bit_equal(self):
        # move the partition to another device BETWEEN batches with
        # windows still open: the carry re-places with no host round
        # trip and the stream stays bit-equal to the oracle
        import jax

        devices = jax.devices()
        if len(devices) < 2:
            pytest.skip("needs the multi-device CPU mesh")
        spec = _spec(keyed=True)
        prt = _partitioned(spec, replica=CarryReplica())
        view, ref = MaterializedView(spec), HostWindowReference(spec)
        batches = self._batches()
        for i, batch in enumerate(batches):
            if i == 1:
                prt.migrate("t", 0, devices[1])
                assert prt.runtime("t", 0).bank.device is devices[1]
            vals = [f"{k} {c}".encode() for k, c, _ in batch]
            d = prt.process_buffer(
                "t", 0, _pack(vals, [s for _, _, s in batch])
            )
            view.apply_delta(d)
            ref.process_batch(batch)
            assert prt.snapshot("t", 0) == ref.bank_entries()
        assert view.table() == ref.table()

    def test_consumer_attach_resync(self):
        # a consumer attaching mid-stream full-resyncs the OPEN table,
        # then follows deltas; open-window state converges exactly
        spec = _spec(lateness_ms=FOREVER)
        rt, ref = _runtime(spec), HostWindowReference(spec)
        rng = np.random.default_rng(43)
        batches = _gen_batches(rng, 3, 16, 1, step=9)
        rt.ingest_arrays(*_cols(batches[0]))
        ref.process_batch(batches[0])
        late_view = MaterializedView(spec)
        late_view.resync(*rt.resync_rows())
        for batch in batches[1:]:
            late_view.apply_delta(rt.ingest_arrays(*_cols(batch)))
            ref.process_batch(batch)
        assert late_view.table() == ref.table()
        assert late_view.resyncs == 1


class TestOverflowPaths:
    def test_emit_overflow_falls_back_to_resync(self):
        # more changed rows than the emit columns: the delta degrades to
        # a full-state image (correct, just not delta-sized) and the
        # view replaces its open table from it
        spec = _spec(emit_capacity=8, lateness_ms=FOREVER)
        rt, view, ref = (
            _runtime(spec), MaterializedView(spec),
            HostWindowReference(spec),
        )
        batch = [(0, i, i * 100) for i in range(40)]  # 40 open windows
        delta = rt.ingest_arrays(*_cols(batch))
        view.apply_delta(delta)
        ref.process_batch(batch)
        assert delta.kind == "resync"
        assert view.resyncs == 1
        assert rt.bank.snapshot() == ref.bank_entries()
        assert view.table() == ref.table()

    def test_delta_disabled_ships_full_state(self):
        # the FLUVIO_WINDOW_DELTA=0 escape hatch: every batch ships the
        # full bank image and the view still converges
        spec = _spec(delta_only=False, lateness_ms=FOREVER)
        rng = np.random.default_rng(47)
        rt, view, ref = (
            _runtime(spec), MaterializedView(spec),
            HostWindowReference(spec),
        )
        for batch in _gen_batches(rng, 3, 16, 1, step=9):
            delta = rt.ingest_arrays(*_cols(batch))
            assert delta.kind == "resync"
            assert delta.delta_bytes >= 0
            view.apply_delta(delta)
            ref.process_batch(batch)
        assert view.table() == ref.table()

    def test_emit_overflow_with_closes_ships_final_aggregates(self):
        # windows CLOSE in the same batch that overflows the emit
        # columns: their final aggregates were evicted from the bank, so
        # the resync must deliver them too (they ride as the emit-column
        # prefix) — the view's closed table still matches host truth
        spec = _spec(emit_capacity=32, lateness_ms=2000)
        rt, view, ref = (
            _runtime(spec), MaterializedView(spec),
            HostWindowReference(spec),
        )
        # 40 windows touched (> emit capacity), wm 3900 closes the
        # first 19 of them in the SAME batch
        b0 = [(0, i + 1, i * 100) for i in range(40)]
        b1 = [(0, 5, 4000)]  # follow-on delta over the resynced view
        deltas = []
        for batch in (b0, b1):
            delta = rt.ingest_arrays(*_cols(batch))
            deltas.append(delta)
            view.apply_delta(delta)
            pins = ref.process_batch(batch)
            assert delta.n_closed == pins["closed"]
            assert rt.bank.snapshot() == ref.bank_entries()
        assert deltas[0].kind == "resync"
        assert deltas[0].n_closed > 0, "overflow batch must close windows"
        assert view.resyncs == 1
        assert view.close_events == len(ref.closed)
        assert view.table() == ref.table()

    def test_delta_disabled_closes_still_delivered(self):
        # FLUVIO_WINDOW_DELTA=0 with windows closing along the way: the
        # per-batch full-state images carry each batch's closes, so the
        # view's closed table converges exactly (not just the open set)
        spec = _spec(delta_only=False, lateness_ms=0)
        rng = np.random.default_rng(53)
        rt, view, ref = (
            _runtime(spec), MaterializedView(spec),
            HostWindowReference(spec),
        )
        for batch in _gen_batches(rng, 4, 16, 1, step=14):
            delta = rt.ingest_arrays(*_cols(batch))
            assert delta.kind == "resync"
            view.apply_delta(delta)
            ref.process_batch(batch)
            assert rt.bank.snapshot() == ref.bank_entries()
        assert ref.closed, "must exercise closes on the full-state path"
        assert view.close_events == len(ref.closed)
        assert view.duplicate_closes == 0
        assert view.table() == ref.table()

    def test_closed_overflow_raises_before_commit(self):
        # more closes in ONE batch than the emit columns hold: the
        # closes cannot be delivered, so the engine fails loud BEFORE
        # committing (like the bank-capacity path) and the carry stays
        spec = _spec(emit_capacity=8, lateness_ms=0)
        rt = _runtime(spec)
        rt.ingest_arrays(*_cols([(0, 1, 0)]))
        before = rt.bank.snapshot()
        wide = [(0, i, i * 100) for i in range(40)]  # closes 39 windows
        with pytest.raises(WindowCapacityError, match="emit"):
            rt.ingest_arrays(*_cols(wide))
        assert rt.bank.snapshot() == before, "overflow must not commit"

    def test_bank_capacity_error_before_commit(self):
        spec = _spec(capacity=4, emit_capacity=8, lateness_ms=FOREVER)
        rt = _runtime(spec)
        rt.ingest_arrays(*_cols([(0, 1, 0), (0, 2, 150)]))
        before = rt.bank.snapshot()
        wide = [(0, i, i * 100) for i in range(10)]
        with pytest.raises(WindowCapacityError):
            rt.ingest_arrays(*_cols(wide))
        assert rt.bank.snapshot() == before, "overflow must not commit"

    def test_restore_rejects_oversized_snapshot(self):
        big = _spec(capacity=64, lateness_ms=FOREVER)
        rt = _runtime(big)
        rt.ingest_arrays(*_cols([(0, i, i * 100) for i in range(20)]))
        entries, wm = rt.bank.snapshot()
        small = _runtime(_spec(capacity=8))
        with pytest.raises(WindowCapacityError):
            small.bank.restore(entries, wm)
